"""Precision handling and the interleaved-real <-> complex boundary.

The reference stores complex data as interleaved double/single pairs and
guarantees std::complex layout compatibility (docs/source/details.rst
"Complex Number Format"). This framework keeps the same boundary format for a
TPU-specific reason as well: complex arrays are not reliably materialisable at
the TPU host boundary, so every jitted transform takes and returns *real*
arrays with a trailing interleaved axis of extent 2 and converts to complex
only inside the traced computation.

Precision names follow the reference's double/single split
(SPFFT_SINGLE_PRECISION, reference CMakeLists.txt:36): "double" = f64/c128
(host/CPU oracle paths; requires jax x64), "single" = f32/c64 (the native TPU
precision).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..errors import InvalidParameterError

_REAL = {"double": np.float64, "single": np.float32}
_COMPLEX = {"double": np.complex128, "single": np.complex64}


def real_dtype(precision: str):
    try:
        return _REAL[precision]
    except KeyError:
        raise InvalidParameterError(
            f"precision must be 'double' or 'single', got {precision!r}")


def complex_dtype(precision: str):
    real_dtype(precision)
    return _COMPLEX[precision]


def interleaved_to_complex(arr):
    """(..., 2) real (traced) -> (...) complex. Jit-safe."""
    return jnp.asarray(arr[..., 0] + 1j * arr[..., 1])


def complex_to_interleaved(arr):
    """(...) complex (traced) -> (..., 2) real. Jit-safe."""
    return jnp.stack([jnp.real(arr), jnp.imag(arr)], axis=-1)


def as_interleaved(arr, precision: str) -> np.ndarray:
    """Coerce host-side input (numpy complex, or real already-interleaved)
    into the canonical (..., 2) real layout at the plan's precision."""
    arr = np.asarray(arr)
    rdt = real_dtype(precision)
    if np.issubdtype(arr.dtype, np.complexfloating):
        out = np.empty(arr.shape + (2,), rdt)
        out[..., 0] = arr.real
        out[..., 1] = arr.imag
        return out
    if arr.ndim >= 1 and arr.shape[-1] == 2:
        return np.ascontiguousarray(arr, rdt)
    raise InvalidParameterError(
        "expected complex array or interleaved real array with trailing "
        f"axis 2, got dtype {arr.dtype} shape {arr.shape}")


def as_complex_np(interleaved) -> np.ndarray:
    """Host-side (..., 2) real -> numpy complex."""
    arr = np.asarray(interleaved)
    cdt = np.complex128 if arr.dtype == np.float64 else np.complex64
    return (arr[..., 0] + 1j * arr[..., 1]).astype(cdt)

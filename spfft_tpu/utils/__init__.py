from .dtypes import (as_complex_np, as_interleaved, complex_dtype,
                     interleaved_to_complex, complex_to_interleaved,
                     real_dtype)  # noqa: F401

"""Canonical benchmark workload generators.

The reference benchmark builds a dense-within-cutoff stick set
(reference: tests/programs/benchmark.cpp:176-205); the driver's north-star
workload is the full spherical cutoff of a plane-wave DFT code. Shared here so
bench.py and the driver entry point cannot diverge on the flagship workload.
"""

from __future__ import annotations

import numpy as np


def spherical_cutoff_triplets(n: int, radius: int | None = None) -> np.ndarray:
    """All (x, y, z) with x^2+y^2+z^2 <= radius^2 in centered indexing
    (default radius n//2) — the plane-wave sphere of a DFT code."""
    c = np.arange(n)
    c = np.where(c > n // 2, c - n, c).astype(np.int32)
    r = n // 2 if radius is None else radius
    X, Y, Z = np.meshgrid(c, c, c, indexing="ij")
    mask = X * X + Y * Y + Z * Z <= r * r
    return np.stack([X[mask], Y[mask], Z[mask]], axis=1)

"""Canonical benchmark workload generators.

The reference benchmark builds a dense-within-cutoff stick set
(reference: tests/programs/benchmark.cpp:176-205); the driver's north-star
workload is the full spherical cutoff of a plane-wave DFT code. Shared here so
bench.py and the driver entry point cannot diverge on the flagship workload.
"""

from __future__ import annotations

import numpy as np


def round_robin_stick_partition(triplets: np.ndarray, dims,
                                num_shards: int) -> list:
    """Assign whole z-sticks round-robin to shards (a stick must live wholly
    on one shard — reference README.md:8). Returns a list of per-shard
    triplet arrays."""
    triplets = np.asarray(triplets)
    _, ny, _ = dims
    storage = np.where(triplets < 0,
                       triplets + np.asarray(dims, triplets.dtype), triplets)
    keys = storage[:, 0].astype(np.int64) * ny + storage[:, 1]
    unique = np.unique(keys)
    owner_of_key = {int(k): i % num_shards
                    for i, k in enumerate(unique.tolist())}
    owners = np.array([owner_of_key[int(k)] for k in keys])
    return [triplets[owners == r] for r in range(num_shards)]


def even_plane_split(dim_z: int, num_shards: int) -> list:
    """Split z planes as evenly as possible (slab heights, sum == dim_z)."""
    base, extra = divmod(dim_z, num_shards)
    return [base + (1 if r < extra else 0) for r in range(num_shards)]


def spherical_cutoff_triplets(n: int, radius: int | None = None) -> np.ndarray:
    """All (x, y, z) with x^2+y^2+z^2 <= radius^2 in centered indexing
    (default radius n//2) — the plane-wave sphere of a DFT code."""
    c = np.arange(n)
    c = np.where(c > n // 2, c - n, c).astype(np.int32)
    r = n // 2 if radius is None else radius
    X, Y, Z = np.meshgrid(c, c, c, indexing="ij")
    mask = X * X + Y * Y + Z * Z <= r * r
    return np.stack([X[mask], Y[mask], Z[mask]], axis=1)


def sort_triplets_stick_major(triplets: np.ndarray, dims) -> np.ndarray:
    """Sort sparse triplets stick-major (by storage (x, y)) and z-ascending
    within each stick — the value order the Pallas compression kernel's
    monotone-gather fast path requires (and the layout the reference
    recommends for performance, docs/source/details.rst "Data
    Distribution"). Returns a new array; the caller's value arrays must be
    reordered the same way."""
    from ..indexing import to_storage_index
    t = np.asarray(triplets).reshape(-1, 3)
    storage = np.stack([to_storage_index(n, t[:, axis])
                        for axis, n in enumerate(dims)], axis=1)
    order = np.lexsort((storage[:, 2],
                        storage[:, 0].astype(np.int64) * dims[1]
                        + storage[:, 1]))
    return t[order]

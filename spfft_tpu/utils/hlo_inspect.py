"""Collective wire-byte extraction from lowered StableHLO text.

Shared by tests/test_compact_exchange.py (which pins the plan-level wire
model to the actually-lowered collectives) and scripts/scaling_model.py
(the recorded 8/16/32-shard projection): one parser, so the falsifiable
scaling table and the test assertions cannot use different accounting.

``collective_permute`` ships one operand-sized buffer per listed
(src, dst) pair; ``all_to_all`` ships (S-1)/S of each shard's operand
off-shard, uniformly.
"""

from __future__ import annotations

import re

import numpy as np

CP_RE = re.compile(
    r'stablehlo\.collective_permute.*?source_target_pairs\s*=\s*dense<'
    r'\[?(?P<pairs>.*?)\]?>\s*:\s*tensor<(?P<npairs>\d+)x2xi64>.*?'
    r'\(tensor<(?P<shape>[^>]*(?:<[^>]*>)?)>\)')
A2A_RE = re.compile(
    r'stablehlo\.all_to_all.*?\(tensor<(?P<shape>[^>]*(?:<[^>]*>)?)>\)')

DTYPE_BYTES = {"complex<f32>": 8, "complex<f64>": 16,
               "f32": 4, "f64": 8, "bf16": 2, "f16": 2}


def tensor_bytes(shape_str: str) -> int:
    """'4x22xcomplex<f64>' -> total bytes."""
    parts = shape_str.split("x")
    dims, i = [], 0
    while i < len(parts) and parts[i].isdigit():
        dims.append(int(parts[i]))
        i += 1
    dtype = "x".join(parts[i:])
    n = 1
    for d in dims:
        n *= d
    return n * DTYPE_BYTES[dtype]


#: StableHLO collective op names this library's exchanges can lower to.
COLLECTIVE_OPS = ("all_to_all", "collective_permute", "all_gather",
                  "ragged_all_to_all")


def count_collectives(txt: str) -> dict:
    """Per-op collective counts in a LOWERED StableHLO module — the
    launch-structure check for the overlap pipeline: ``overlap_chunks=K``
    must lower K collectives per direction (one per chunk) where the
    monolithic path lowers one. Counts every spelling this library's
    exchange mechanisms produce (``COLLECTIVE_OPS``)."""
    return {op: len(re.findall(rf"stablehlo\.{op}\b", txt))
            for op in COLLECTIVE_OPS}


def total_collectives(txt: str) -> int:
    """Sum of :func:`count_collectives` — the module's collective launch
    count."""
    return sum(count_collectives(txt).values())


def collective_async_split(txt: str) -> dict:
    """Count asynchronous collective start/done pairs in an OPTIMIZED
    HLO module (``lowered.compile().as_text()``) — the structural
    evidence that the backend scheduler actually split a collective so
    compute can run between its start and its done (XLA's latency-hiding
    scheduler emits ``<op>-start``/``<op>-done`` — or wraps the op in
    ``async-start``/``async-done`` — only when the dependence graph
    leaves something to overlap; the overlap pipeline's chunk loop
    exists to create exactly that slack). Returns
    ``{"starts": n, "dones": n, "by_op": {...}}``; all zero on backends
    that schedule collectives synchronously (XLA:CPU today), which is
    why the TPU CI lane owns the hard assertion."""
    by_op = {}
    for op in ("all-to-all", "collective-permute", "all-gather",
               "ragged-all-to-all"):
        n = len(re.findall(rf"{op}-start", txt))
        if n:
            by_op[op] = n
    async_n = len(re.findall(r"async-start", txt))
    if async_n:
        by_op["async"] = async_n
    starts = sum(by_op.values())
    dones = (sum(len(re.findall(rf"{op}-done", txt))
                 for op in ("all-to-all", "collective-permute",
                            "all-gather", "ragged-all-to-all"))
             + len(re.findall(r"async-done", txt)))
    return {"starts": starts, "dones": dones, "by_op": by_op}


def hlo_wire_bytes(txt: str, num_shards: int):
    """(total_off_shard_bytes, per_shard_sent, per_shard_recv) summed over
    every collective in one lowered SPMD module."""
    sent = np.zeros(num_shards, np.int64)
    recv = np.zeros(num_shards, np.int64)
    for m in CP_RE.finditer(txt):
        nbytes = tensor_bytes(m.group("shape"))
        flat = [int(v) for v in re.findall(r"-?\d+", m.group("pairs"))]
        for s, d in zip(flat[::2], flat[1::2]):
            if s != d:
                sent[s] += nbytes
                recv[d] += nbytes
    for m in A2A_RE.finditer(txt):
        nbytes = tensor_bytes(m.group("shape"))
        off = nbytes * (num_shards - 1) // num_shards
        sent += off
        recv += off
    return int(sent.sum()), sent, recv

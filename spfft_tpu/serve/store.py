"""Persistent plan-artifact store: zero-cold-start serving.

Every process today pays plan construction from scratch: index-table
construction (~0.35 s at 256^3, BENCH_r05 ``plan_s``), the background
compression-table build (native cover builds, seconds at large sizes)
and per-signature jit trace/compile. The XLA persistent compilation
cache (``utils.platform.enable_persistent_compilation_cache``) softens
only the *compile* third — nothing persists the plan half, and at
fleet scale (autoscaling, restarts, spot preemption) the cold start is
the dominant tail. This module is the missing tier: a content-addressed
on-disk store of

    ``PlanSignature`` -> { index tables, gather/fused kernel tables,
                           plan metadata, optionally ``jax.export``-
                           serialized AOT executables }

that a REPLACEMENT PROCESS loads at boot instead of rebuilding. A warm
load reconstructs a :class:`~spfft_tpu.plan.TransformPlan` through
:func:`spfft_tpu.plan.restore_plan` — no ``build_index_plan``, no
background table-build thread, only the device commit of prebuilt
tables (``PlanRegistry.get_or_build`` resolves with ``builds == 0``).

Artifact format (one file per signature, ``artifacts/<key>.plan``):

    MAGIC line | 16-hex header length | JSON header | npz payload

* the header carries format + table-schema versions, the full
  canonical signature, reconstruction metadata, and the SHA-256 of the
  payload bytes;
* the payload is an ``np.savez`` archive: ``value_indices`` /
  ``stick_keys`` (the index plan), the gather/fused table dataclasses
  field-by-field, and the AOT blobs as uint8 arrays (covered by the
  payload checksum like everything else).

Safety contract (tier-1 tested, tests/test_plan_store.py): a poisoned
artifact NEVER loads — truncated/corrupt bytes, a format or
table-schema version mismatch, a payload checksum failure, or an index
digest that no longer matches the stored tables all reject with a
typed reason (``spfft_store_rejects_total{reason}``) and the caller
falls back to a clean rebuild. Writes are atomic (temp file +
``os.replace``), so a concurrent writer race or a crash mid-spill can
leave at worst a stale-but-complete artifact, never a torn one.

Request aliases (``requests/<key>.json``) map the digest of a RAW
request (transform type, dims, precision, scaling, triplet bytes) to
its canonical artifact, so a fresh process resolves a request without
computing the signature — the piece that makes ``get_or_build`` warm
loads possible before any index plan exists in the process.

CLI (``python -m spfft_tpu.serve.store``): ``manifest`` records the
store's signatures for boot prewarm, ``prewarm`` warm-loads everything
into a fresh registry (optionally compiling, optionally checking
bit-exactness against a recorded reference), ``gc`` enforces the byte
cap, ``verify`` integrity-checks every artifact, ``seed`` builds one
canonical workload into the store (the cold half of ``make
store-smoke``). See docs/artifact_cache.md.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import io
import json
import os
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import faults as _faults
from .. import obs as _obs
from ..errors import BlobStoreError, InvalidParameterError
from ..indexing import IndexPlan
from ..plan import PlanTables, TransformPlan, restore_plan
from ..types import Scaling, TransformType
from .registry import PlanSignature, index_digest

#: Default store location for every registry in the process (see
#: ``PlanRegistry``); the config's ``plan_store_path`` (settable via
#: the boot artifact) takes precedence when set.
PLAN_STORE_ENV = "SPFFT_TPU_PLAN_STORE"

#: Default REMOTE artifact tier (``net/blobstore.py``): an ``http://``
#: object-store URL or a shared directory. The config's
#: ``blob_store_url`` path setting takes precedence when set. The
#: remote tier sits BELOW the disk tier: a local miss consults it
#: through the same digest/version gauntlet, a successful spill
#: publishes to it best-effort — it is an optimisation (an autoscaled
#: worker boots warm off the fleet's shared artifact set), never a
#: correctness dependency.
BLOB_STORE_ENV = "SPFFT_TPU_BLOB_STORE"

#: Live boot-prewarm manifest: when set, every successful spill merges
#: its entry into the manifest at this path (read -> dedupe by
#: artifact key -> atomic replace), so the manifest a replacement
#: process prewarms from tracks the fleet's working set WITHOUT a
#: periodic ``python -m spfft_tpu.serve.store manifest`` sweep. The
#: same spelling is the executor's boot-prewarm source
#: (``ServeExecutor`` reads it through ``executor.PLAN_MANIFEST_ENV``).
PLAN_MANIFEST_ENV = "SPFFT_TPU_PLAN_MANIFEST"

#: Serializes live-manifest read/merge/replace cycles: the env var
#: names ONE file shared by every store object in the process, so the
#: append path locks process-wide, not per-store. Across processes the
#: atomic replace keeps the file untorn (a concurrent writer can lose
#: an update to the read-modify-write race, never corrupt the file —
#: the losing entry re-merges on that plan's next spill).
_MANIFEST_LOCK = threading.Lock()

#: ``0`` disables AOT executable export on spill (artifacts then carry
#: tables only). Deserialize failures are always non-fatal: the plan
#: loads and falls back to a fresh jit.
AOT_ENV = "SPFFT_TPU_PLAN_STORE_AOT"

MAGIC = b"SPFFT-TPU-PLAN-ARTIFACT\n"
#: Container format version: bumped on any change to the byte layout.
FORMAT_VERSION = 1
#: Table schema version: bumped when the serialized table dataclasses
#: (gather_kernel.*GatherTables, fused_kernel.Fused*Tables) change
#: fields — an old artifact then rejects cleanly instead of
#: reconstructing garbage.
TABLE_SCHEMA = 2  # 2: FusedDecompressTables.zinfo (r2c completion)

MANIFEST_KEY = "spfft_tpu_plan_manifest"
MANIFEST_VERSION = 1
REQUEST_KEY = "spfft_tpu_plan_request"

#: Typed rejection reasons (the ``reason`` label of
#: ``spfft_store_rejects_total``).
REASON_CORRUPT = "corrupt"            # bytes/JSON/npz/checksum damage
REASON_VERSION = "version_mismatch"   # format or table-schema version
REASON_DIGEST = "digest_mismatch"     # stored index digest is stale
REASON_IO = "io"                      # unreadable file
REASON_INCOMPATIBLE = "incompatible"  # caller kwargs the artifact
                                      # cannot honour (rebuild instead)
REASON_DEGRADED = "degraded"          # spill skipped: memory-only tier

#: Store I/O degradation ladder (docs/artifact_cache.md): a TRANSIENT
#: I/O error gets IO_RETRIES bounded retries with IO_BACKOFF_S
#: geometric backoff; a PERSISTENT disk fault (ENOSPC, read-only or
#: corrupt volume — faults.PERSISTENT_DISK_ERRNOS) flips the store to
#: the memory-only tier, re-probed every REPROBE_INTERVAL_S (doubling
#: to REPROBE_MAX_INTERVAL_S while the disk stays broken).
IO_RETRIES = 2
IO_BACKOFF_S = 0.05
REPROBE_INTERVAL_S = 30.0
REPROBE_MAX_INTERVAL_S = 480.0


def aot_enabled() -> bool:
    """AOT executable export is on unless ``SPFFT_TPU_PLAN_STORE_AOT=0``."""
    return os.environ.get(AOT_ENV, "1") != "0"


# -- table dataclass (de)serialization ---------------------------------------
def _table_kinds() -> Dict[str, type]:
    from ..ops import fused_kernel as fkm
    from ..ops import gather_kernel as gk
    return {"monotone": gk.MonotoneGatherTables,
            "wide": gk.WideGatherTables,
            "fused_dec": fkm.FusedDecompressTables,
            "fused_cmp": fkm.FusedCompressTables}


def _kind_name(obj) -> str:
    for name, cls in _table_kinds().items():
        if type(obj) is cls:
            return name
    raise InvalidParameterError(
        f"unknown plan-table type {type(obj).__name__}")


def _pack_tables(obj, prefix: str, arrays: dict, tables_meta: dict) -> None:
    """Flatten one frozen table dataclass into the npz array dict
    (ndarray fields, plus ``segs`` as an (n, 4) int64 array) and the
    header's scalar metadata."""
    meta = {"kind": _kind_name(obj)}
    for f in dataclasses.fields(obj):
        v = getattr(obj, f.name)
        if isinstance(v, np.ndarray):
            arrays[f"{prefix}.{f.name}"] = v
        elif f.name == "segs":
            arrays[f"{prefix}.segs"] = \
                np.asarray(v, np.int64).reshape(-1, 4)
        elif v is None:
            pass  # Optional field at its default — omitted entirely
        else:
            meta[f.name] = int(v)
    tables_meta[prefix] = meta


def _unpack_tables(prefix: str, arrays: dict, tables_meta: dict):
    meta = tables_meta[prefix]
    cls = _table_kinds()[meta["kind"]]
    kwargs = {}
    for f in dataclasses.fields(cls):
        key = f"{prefix}.{f.name}"
        if f.name == "segs":
            segs = arrays[key]
            kwargs["segs"] = tuple(tuple(int(x) for x in row)
                                   for row in segs)
        elif key in arrays:
            kwargs[f.name] = arrays[key]
        elif f.name in meta:
            kwargs[f.name] = meta[f.name]
        # else: Optional field serialized at its None default
    return cls(**kwargs)


# -- keys --------------------------------------------------------------------
def signature_key(sig: PlanSignature) -> str:
    """Content-derived artifact key: SHA-256 over the canonical
    signature fields (the index digest already summarises the sparse
    set, so equal keys mean interchangeable plans)."""
    h = hashlib.sha256()
    h.update("|".join(str(v) for v in dataclasses.astuple(sig)).encode())
    return h.hexdigest()


def request_key(transform_type, dim_x: int, dim_y: int, dim_z: int,
                triplets: np.ndarray, precision: str,
                scaling) -> str:
    """Digest of a RAW request (exact triplet bytes, caller order) —
    the alias key a fresh process can compute without building any
    index plan. Unlike the canonical signature it is representation
    sensitive (centered vs wrapped spellings get two aliases), mirroring
    the registry's raw-bytes memo."""
    arr = np.ascontiguousarray(np.asarray(triplets))
    h = hashlib.sha256()
    h.update(f"{TransformType(transform_type).value}|{dim_x}|{dim_y}|"
             f"{dim_z}|{precision}|{Scaling(scaling).value}|"
             f"{arr.dtype.str}|{arr.shape}".encode())
    h.update(arr.tobytes())
    return h.hexdigest()


@dataclasses.dataclass(frozen=True)
class StoreReject(Exception):
    """Internal: one typed artifact rejection (reason + detail)."""

    reason: str
    detail: str

    def __str__(self) -> str:
        return f"{self.reason}: {self.detail}"


# -- artifact serialization --------------------------------------------------
def serialize_artifact(sig: PlanSignature, plan: TransformPlan,
                       aot_blobs: Optional[Dict[str, bytes]] = None
                       ) -> bytes:
    """The full artifact byte string for one (signature, plan) pair."""
    tabs = plan.export_tables()
    p = plan.index_plan
    arrays: dict = {
        "value_indices": np.ascontiguousarray(p.value_indices),
        "stick_keys": np.ascontiguousarray(p.stick_keys),
    }
    if p.value_conj is not None:
        arrays["value_conj"] = np.ascontiguousarray(
            p.value_conj.astype(np.uint8))
    tables_meta: dict = {}
    if tabs.pallas_box:
        for which, t in tabs.pallas_box.items():
            if t is not None:
                _pack_tables(t, f"pal.{which}", arrays, tables_meta)
    for which, t in (tabs.fused_box or {}).items():
        if t is not None:
            _pack_tables(t, f"fus.{which}", arrays, tables_meta)
    aot_meta = {}
    for key, blob in (aot_blobs or {}).items():
        arrays[f"aot.{key}"] = np.frombuffer(blob, np.uint8)
        aot_meta[key] = len(blob)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    payload = buf.getvalue()
    import jax
    header = {
        "format_version": FORMAT_VERSION,
        "table_schema": TABLE_SCHEMA,
        "signature": dataclasses.asdict(sig),
        "meta": {
            "transform_type": p.transform_type.value,
            "dim_x": p.dim_x, "dim_y": p.dim_y, "dim_z": p.dim_z,
            "centered": bool(p.centered),
            "precision": plan.precision,
            "s_pad": int(plan._s_pad),
            "num_values": p.num_values,
            "num_sticks": p.num_sticks,
            "fused_reasons": dict(tabs.fused_reasons),
            "tables": tables_meta,
            "aot": aot_meta,
            "backend": jax.default_backend(),
            "created_unix": time.time(),
        },
        "payload_sha256": hashlib.sha256(payload).hexdigest(),
        "payload_len": len(payload),
    }
    hbytes = json.dumps(header, sort_keys=True).encode()
    return b"".join([MAGIC, b"%016x\n" % len(hbytes), hbytes, payload])


def parse_artifact(data: bytes) -> Tuple[dict, dict]:
    """``(header, arrays)`` from artifact bytes, or raise
    :class:`StoreReject` with the typed reason. Every check the safety
    contract names runs here: magic, header parse, version match,
    payload checksum, npz parse, and the index-digest recomputation."""
    if not data.startswith(MAGIC):
        raise StoreReject(REASON_CORRUPT, "bad magic")
    off = len(MAGIC)
    try:
        hlen = int(data[off:off + 16], 16)
    except ValueError:
        raise StoreReject(REASON_CORRUPT, "bad header length")
    off += 17  # 16 hex chars + newline
    try:
        header = json.loads(data[off:off + hlen])
    except ValueError:
        raise StoreReject(REASON_CORRUPT, "header is not JSON")
    if not isinstance(header, dict):
        raise StoreReject(REASON_CORRUPT, "header is not a mapping")
    if header.get("format_version") != FORMAT_VERSION:
        raise StoreReject(
            REASON_VERSION,
            f"format_version {header.get('format_version')!r} != "
            f"{FORMAT_VERSION}")
    if header.get("table_schema") != TABLE_SCHEMA:
        raise StoreReject(
            REASON_VERSION,
            f"table_schema {header.get('table_schema')!r} != "
            f"{TABLE_SCHEMA}")
    payload = data[off + hlen:]
    if len(payload) != header.get("payload_len"):
        raise StoreReject(
            REASON_CORRUPT,
            f"payload is {len(payload)} bytes, header says "
            f"{header.get('payload_len')}")
    if hashlib.sha256(payload).hexdigest() != header.get("payload_sha256"):
        raise StoreReject(REASON_CORRUPT, "payload checksum mismatch")
    try:
        with np.load(io.BytesIO(payload)) as z:
            arrays = {k: z[k] for k in z.files}
    except Exception as exc:
        raise StoreReject(REASON_CORRUPT, f"payload unreadable: {exc!r}")
    for need in ("value_indices", "stick_keys"):
        if need not in arrays:
            raise StoreReject(REASON_CORRUPT, f"payload lacks {need}")
    # index-digest recomputation: the stored tables must still describe
    # the signature they claim to — a stale or swapped payload that
    # passes the checksum (e.g. a hand-edited artifact) rejects here
    # rather than loading a wrong-answer plan.
    ip = _index_plan_of(header, arrays)
    want = header.get("signature", {}).get("index_digest")
    got = index_digest(ip)
    if got != want:
        raise StoreReject(
            REASON_DIGEST, f"stored index digest {str(want)[:12]}... "
            f"but tables digest to {got[:12]}...")
    meta = header["meta"]
    if ip.num_values != meta.get("num_values") \
            or ip.num_sticks != meta.get("num_sticks") \
            or int(meta.get("s_pad", -1)) < ip.num_sticks:
        raise StoreReject(REASON_CORRUPT, "table geometry inconsistent")
    return header, arrays


def _index_plan_of(header: dict, arrays: dict) -> IndexPlan:
    meta = header.get("meta", {})
    try:
        return IndexPlan(
            transform_type=TransformType(meta["transform_type"]),
            dim_x=int(meta["dim_x"]), dim_y=int(meta["dim_y"]),
            dim_z=int(meta["dim_z"]), centered=bool(meta["centered"]),
            value_indices=arrays["value_indices"],
            stick_keys=arrays["stick_keys"],
            value_conj=(arrays["value_conj"].astype(bool)
                        if "value_conj" in arrays else None))
    except (KeyError, ValueError) as exc:
        raise StoreReject(REASON_CORRUPT, f"bad index metadata: {exc!r}")


def _plan_tables_of(header: dict, arrays: dict) -> PlanTables:
    meta = header["meta"]
    tables_meta = meta.get("tables", {})
    try:
        pal = {}
        for which in ("dec", "cmp"):
            if f"pal.{which}" in tables_meta:
                pal[which] = _unpack_tables(f"pal.{which}", arrays,
                                            tables_meta)
            else:
                pal[which] = None
        fus = {}
        for which in ("dec", "cmp"):
            if f"fus.{which}" in tables_meta:
                fus[which] = _unpack_tables(f"fus.{which}", arrays,
                                            tables_meta)
            else:
                fus[which] = None
    except (KeyError, TypeError, ValueError) as exc:
        raise StoreReject(REASON_CORRUPT, f"bad table payload: {exc!r}")
    box = pal if (pal["dec"] is not None or pal["cmp"] is not None) \
        else None
    return PlanTables(s_pad=int(meta["s_pad"]), pallas_box=box,
                      fused_box=fus,
                      fused_reasons=dict(meta.get("fused_reasons", {})))


# -- AOT executables ---------------------------------------------------------
def export_aot_blobs(plan: TransformPlan) -> Dict[str, bytes]:
    """``jax.export``-serialize the plan's executables: the three
    single-request entries (backward, forward NONE, forward FULL), the
    three batched entries over a SYMBOLIC batch dimension (one exported
    module serves every batch size — the serving executor's fused
    batches hit it without per-B re-export), and the two identity
    fused-pair entries (``apply_pointwise`` with ``fn=None``, NONE and
    FULL scaling — the reference benchmark's backward+forward round
    trip). Best-effort: any entry that fails to export is simply absent
    (the restored plan jits it fresh). Double-single plans export
    nothing (their host-side split/combine boundary is not a single
    traced function)."""
    if getattr(plan, "_ds", False):
        return {}
    try:
        import jax
        from jax import export as jax_export
    except ImportError:
        return {}
    plan._finalize()
    tab_avals = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
        dict(plan._tables_hot))
    try:
        vshape, vdtype = plan.batch_row_template("values")
        sshape, sdtype = plan.batch_row_template("space")
    except Exception:
        return {}
    batched = plan._batched_jits()
    # un-donated pair jits: the store's copy must not inherit the
    # caller's donate_inputs buffer reuse
    pair_none = jax.jit(functools.partial(plan._pair_impl, scaled=False,
                                          fn=None))
    pair_full = jax.jit(functools.partial(plan._pair_impl, scaled=True,
                                          fn=None))
    b, = jax_export.symbolic_shape("b")
    entries = (
        ("backward", plan._backward_jit,
         jax.ShapeDtypeStruct(vshape, vdtype)),
        ("forward_none", plan._forward_jit[Scaling.NONE],
         jax.ShapeDtypeStruct(sshape, sdtype)),
        ("forward_full", plan._forward_jit[Scaling.FULL],
         jax.ShapeDtypeStruct(sshape, sdtype)),
        ("batched_backward", batched["backward"],
         jax.ShapeDtypeStruct((b, *vshape), vdtype)),
        ("batched_forward_none", batched[Scaling.NONE],
         jax.ShapeDtypeStruct((b, *sshape), sdtype)),
        ("batched_forward_full", batched[Scaling.FULL],
         jax.ShapeDtypeStruct((b, *sshape), sdtype)),
        ("pair_none", pair_none, jax.ShapeDtypeStruct(vshape, vdtype)),
        ("pair_full", pair_full, jax.ShapeDtypeStruct(vshape, vdtype)),
    )
    out = {}
    for key, jitted, aval in entries:
        try:
            out[key] = jax_export.export(jitted)(aval,
                                                 tab_avals).serialize()
        except Exception as exc:
            _obs.record_store_aot_skip("export_failed")
            import logging
            logging.getLogger("spfft_tpu").info(
                "spfft_tpu: AOT export of %s skipped (%r)", key, exc)
    return out


def _install_aot(plan: TransformPlan, header: dict, arrays: dict) -> int:
    """Deserialize and install whatever AOT blobs the artifact carries
    and this backend can run. Non-fatal by design: any failure skips
    that executable (counted), the plan still serves through fresh
    jits. Returns the number installed."""
    aot_meta = header["meta"].get("aot") or {}
    if not aot_meta:
        return 0
    # fault seam: an injected failure here flows into load_key's
    # poisoned-restore handling -> typed CORRUPT reject + clean rebuild
    _faults.check_site("store.aot")
    try:
        import jax
        from jax import export as jax_export
    except ImportError:
        _obs.record_store_aot_skip("jax_export_unavailable")
        return 0
    backend = jax.default_backend()
    installed = {}
    for key in aot_meta:
        blob = arrays.get(f"aot.{key}")
        if blob is None:
            _obs.record_store_aot_skip("blob_missing")
            continue
        try:
            exported = jax_export.deserialize(blob.tobytes())
        except Exception:
            _obs.record_store_aot_skip("deserialize_failed")
            continue
        if backend not in exported.platforms:
            _obs.record_store_aot_skip("platform_mismatch")
            continue
        installed[key] = exported
    if installed:
        plan.install_aot(installed)
    return len(installed)


class PlanArtifactStore:
    """Content-addressed on-disk store of plan artifacts.

    ``root`` holds ``artifacts/<signature key>.plan`` plus
    ``requests/<request key>.json`` aliases. ``max_bytes`` bounds the
    artifacts' total size (``None`` resolves through the control
    plane's ``plan_store_max_bytes`` knob; 0 = unbounded): every save
    triggers an oldest-mtime GC sweep that never removes the artifact
    just written. All writes are atomic; concurrent writers of the
    same key are idempotent (same content, last ``os.replace`` wins).
    """

    def __init__(self, root: str, max_bytes: Optional[int] = None,
                 remote=None):
        self.root = str(root)
        self._max_bytes = max_bytes
        self._lock = threading.Lock()
        # remote blob tier below disk: None resolves lazily through
        # the config/env (first use, not construction — the agent CLI
        # sets blob_store_url after stores may already exist); False
        # disables; a str/BlobStore pins it.
        self._remote_spec = remote
        self._remote_obj = None    #: guarded by _lock
        self._remote_ready = False  #: guarded by _lock
        self._hits = 0    #: guarded by _lock
        self._misses = 0  #: guarded by _lock
        self._spills = 0  #: guarded by _lock
        self._rejects: Dict[str, int] = {}  #: guarded by _lock
        #: guarded by _lock
        self._spill_threads: List[threading.Thread] = []
        self._degraded_reason: Optional[str] = None  #: guarded by _lock
        self._degraded_since = 0.0  #: guarded by _lock
        self._reprobe_at = 0.0      #: guarded by _lock
        #: guarded by _lock
        self._reprobe_interval = REPROBE_INTERVAL_S
        self._io_retries = 0        #: guarded by _lock
        os.makedirs(self._dir("artifacts"), exist_ok=True)
        os.makedirs(self._dir("requests"), exist_ok=True)

    def _dir(self, kind: str) -> str:
        return os.path.join(self.root, kind)

    def artifact_path(self, key: str) -> str:
        return os.path.join(self._dir("artifacts"), f"{key}.plan")

    def request_path(self, rkey: str) -> str:
        return os.path.join(self._dir("requests"), f"{rkey}.json")

    @property
    def max_bytes(self) -> int:
        if self._max_bytes is not None:
            return int(self._max_bytes)
        from ..control.config import global_config
        return int(global_config().plan_store_max_bytes)

    # -- counters ----------------------------------------------------------
    def _count(self, what: str, reason: Optional[str] = None) -> None:
        with self._lock:
            if what == "hit":
                self._hits += 1
            elif what == "miss":
                self._misses += 1
            elif what == "spill":
                self._spills += 1
            elif what == "reject":
                self._rejects[reason] = self._rejects.get(reason, 0) + 1
        _obs.record_store(what, reason)

    def stats(self) -> Dict:
        with self._lock:
            return {"hits": self._hits, "misses": self._misses,
                    "spills": self._spills,
                    "rejects": dict(self._rejects)}

    # -- degradation ladder ------------------------------------------------
    def _degrade(self, exc: BaseException) -> None:
        """Flip to the MEMORY-ONLY tier after a persistent disk fault:
        spills are skipped (the registry's LRU keeps serving), loads
        still attempt (per-artifact failures reject to clean rebuilds),
        ``health()`` reports degraded, and a periodic re-probe checks
        whether the volume recovered."""
        now = time.monotonic()
        with self._lock:
            fresh = self._degraded_reason is None
            self._degraded_reason = f"{type(exc).__name__}: {exc}"
            if fresh:
                self._degraded_since = now
                self._reprobe_interval = REPROBE_INTERVAL_S
            else:
                self._reprobe_interval = min(
                    self._reprobe_interval * 2, REPROBE_MAX_INTERVAL_S)
            self._reprobe_at = now + self._reprobe_interval
            interval = self._reprobe_interval
        _obs.GLOBAL_COUNTERS.set("spfft_store_degraded", 1.0)
        _obs.record_event("store.degrade",
                          reason=f"{type(exc).__name__}: {exc}",
                          interval_s=interval)
        import logging
        logging.getLogger("spfft_tpu").warning(
            "spfft_tpu: plan-artifact store degraded to memory-only "
            "(%r) — spills disabled, re-probe in %.0f s", exc, interval)

    def _maybe_reprobe(self) -> None:
        """While degraded, probe the volume once per backoff interval:
        an atomic probe write that succeeds lifts the degradation; a
        failure doubles the interval (capped)."""
        with self._lock:
            if self._degraded_reason is None \
                    or time.monotonic() < self._reprobe_at:
                return
            # claim this probe slot so concurrent callers don't stack
            self._reprobe_at = time.monotonic() + self._reprobe_interval
        probe = os.path.join(self.root, ".reprobe")
        try:
            self._atomic_write_once(probe, b"probe")
            os.unlink(probe)
        except Exception:
            self._degrade_extend()
            _obs.GLOBAL_COUNTERS.inc("spfft_store_reprobes_total",
                                     outcome="failed")
            _obs.record_event("store.reprobe", outcome="failed")
            return
        with self._lock:
            self._degraded_reason = None
            self._degraded_since = 0.0
            self._reprobe_interval = REPROBE_INTERVAL_S
        _obs.GLOBAL_COUNTERS.set("spfft_store_degraded", 0.0)
        _obs.GLOBAL_COUNTERS.inc("spfft_store_reprobes_total",
                                 outcome="recovered")
        _obs.record_event("store.reprobe", outcome="recovered")
        import logging
        logging.getLogger("spfft_tpu").warning(
            "spfft_tpu: plan-artifact store disk re-probe succeeded — "
            "memory-only degradation lifted, spills re-enabled")

    def _degrade_extend(self) -> None:
        with self._lock:
            self._reprobe_interval = min(
                self._reprobe_interval * 2, REPROBE_MAX_INTERVAL_S)
            self._reprobe_at = time.monotonic() + self._reprobe_interval

    @property
    def degraded(self) -> bool:
        """True while the store runs the memory-only tier."""
        with self._lock:
            return self._degraded_reason is not None

    def health(self) -> Dict:
        """Liveness snapshot for operators and the executor's
        ``health()``: ``state`` is ``"ok"`` or ``"degraded"``
        (memory-only tier after a persistent disk fault), with the
        triggering reason, how long it has been degraded, and when the
        next disk re-probe is due."""
        with self._lock:
            if self._degraded_reason is None:
                return {"state": "ok", "mode": "disk",
                        "io_retries": self._io_retries}
            now = time.monotonic()
            return {
                "state": "degraded",
                "mode": "memory-only",
                "reason": self._degraded_reason,
                "degraded_for_s": round(now - self._degraded_since, 3),
                "next_probe_in_s": round(
                    max(0.0, self._reprobe_at - now), 3),
                "io_retries": self._io_retries,
            }

    def _check(self, site: str) -> None:
        """Fault checkpoint that classifies like real I/O: an injected
        persistent disk fault (the ``enospc`` kind) degrades the store
        exactly as a genuine one surfacing from the filesystem would."""
        try:
            _faults.check_site(site)
        except OSError as exc:
            if _faults.is_persistent_disk_error(exc):
                self._degrade(exc)
            raise

    def _retry_io(self, op: str, fn):
        """Run one I/O operation under the degradation ladder: a
        transient ``OSError`` (EINTR, a brief NFS hiccup — anything
        outside :data:`~spfft_tpu.faults.PERSISTENT_DISK_ERRNOS`) gets
        :data:`IO_RETRIES` bounded retries with geometric backoff; a
        persistent disk fault degrades the store to memory-only and
        re-raises for the caller's typed handling."""
        delay = IO_BACKOFF_S
        for attempt in range(IO_RETRIES + 1):
            try:
                return fn()
            except FileNotFoundError:
                raise  # a miss, not an I/O fault
            except OSError as exc:
                if _faults.is_persistent_disk_error(exc):
                    self._degrade(exc)
                    raise
                if attempt >= IO_RETRIES:
                    raise
                with self._lock:
                    self._io_retries += 1
                _obs.GLOBAL_COUNTERS.inc("spfft_store_io_retries_total",
                                         op=op)
                time.sleep(delay)
                delay *= 2

    # -- writing -----------------------------------------------------------
    def _atomic_write_once(self, path: str, data: bytes) -> None:
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                   prefix=".tmp-")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
                f.flush()
                _faults.check_site("store.fsync")
                os.fsync(f.fileno())
            _faults.check_site("store.replace")
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def _atomic_write(self, path: str, data: bytes) -> None:
        self._retry_io("write",
                       lambda: self._atomic_write_once(path, data))

    def save_plan(self, sig: PlanSignature, plan: TransformPlan,
                  triplets=None, aot: Optional[bool] = None) -> str:
        """Serialize and atomically write one artifact (plus a request
        alias when the raw ``triplets`` are given). Returns the
        artifact key. While the store is DEGRADED (memory-only tier)
        the write is skipped — counted under
        ``spfft_store_rejects_total{reason=degraded}`` — unless the
        periodic re-probe just lifted the degradation."""
        t0 = time.perf_counter()
        self._check("store.spill")
        self._maybe_reprobe()
        key = signature_key(sig)
        if self.degraded:
            self._count("reject", REASON_DEGRADED)
            return key
        if aot is None:
            aot = aot_enabled()
        blobs = export_aot_blobs(plan) if aot else {}
        data = serialize_artifact(sig, plan, blobs)
        self._atomic_write(self.artifact_path(key), data)
        self._remote_publish(f"art/{key}.plan", data)
        if triplets is not None:
            rkey = request_key(sig.transform_type, sig.dim_x, sig.dim_y,
                               sig.dim_z, triplets, sig.precision,
                               sig.scaling)
            alias = {REQUEST_KEY: 1, "artifact": key,
                     "signature": dataclasses.asdict(sig)}
            alias_bytes = json.dumps(alias).encode()
            self._atomic_write(self.request_path(rkey), alias_bytes)
            self._remote_publish(f"req/{rkey}.json", alias_bytes)
        self._count("spill")
        _obs.record_compile("store_spill", time.perf_counter() - t0, t0,
                            key=key[:12], bytes=len(data),
                            aot=bool(blobs))
        manifest = os.environ.get(PLAN_MANIFEST_ENV)
        if manifest:
            self._refresh_manifest(manifest, key, sig, plan,
                                   len(data), blobs)
        if self.max_bytes:
            self.gc(keep=key)
        return key

    def _refresh_manifest(self, path: str, key: str,
                          sig: PlanSignature, plan: TransformPlan,
                          nbytes: int, blobs: Dict) -> None:
        """Merge this spill into the live boot-prewarm manifest. Never
        fails the spill: a broken manifest file is a warning plus an
        ``io`` reject, and the next sweep (``python -m
        spfft_tpu.serve.store manifest``) rebuilds it from the store."""
        entry = {
            "artifact": key,
            "signature": dataclasses.asdict(sig),
            "dims": [sig.dim_x, sig.dim_y, sig.dim_z],
            "num_values": plan.index_plan.num_values,
            "precision": sig.precision,
            "bytes": nbytes,
            "aot": sorted(blobs or ()),
        }
        try:
            self.append_manifest_entry(path, entry)
            self._count("manifest_refresh")
        except (OSError, InvalidParameterError) as exc:
            self._count("reject", REASON_IO)
            import logging
            logging.getLogger("spfft_tpu").warning(
                "spfft_tpu: live manifest refresh failed (%r)", exc)

    def spill_async(self, sig: PlanSignature, plan: TransformPlan,
                    triplets=None) -> threading.Thread:
        """Write-behind spill on a daemon thread (the registry's build
        path must not serialize MBs of tables on the serving thread).
        Failures are swallowed into a reject counter — a broken disk
        must never fail a successful build."""
        snapshot = None if triplets is None \
            else np.ascontiguousarray(np.asarray(triplets)).copy()

        def run():
            try:
                self.save_plan(sig, plan, snapshot)
            except Exception as exc:
                self._count("reject", REASON_IO)
                import logging
                logging.getLogger("spfft_tpu").warning(
                    "spfft_tpu: plan-artifact spill failed (%r)", exc)

        th = threading.Thread(target=run, daemon=True,
                              name="spfft-plan-spill")
        with self._lock:
            self._spill_threads = [t for t in self._spill_threads
                                   if t.is_alive()]
            self._spill_threads.append(th)
        th.start()
        return th

    def drain(self) -> None:
        """Join all in-flight write-behind spills (tests, shutdown)."""
        with self._lock:
            threads = list(self._spill_threads)
        for th in threads:
            th.join()

    # -- the remote blob tier ----------------------------------------------
    def _remote_tier(self):
        """The resolved remote blob tier, or None. Resolution is lazy
        and cached: an explicit ``remote=`` ctor value wins, otherwise
        the control plane's ``blob_store_url`` path setting, otherwise
        the ``SPFFT_TPU_BLOB_STORE`` env var; empty everywhere means
        no remote tier."""
        with self._lock:
            if not self._remote_ready:
                self._remote_ready = True
                spec = self._remote_spec
                if spec is None:
                    from ..control.config import global_config
                    spec = global_config().blob_store_url \
                        or os.environ.get(BLOB_STORE_ENV, "")
                if spec is False:
                    spec = ""
                if isinstance(spec, str):
                    from ..net.blobstore import open_blobstore
                    self._remote_obj = open_blobstore(spec)
                else:
                    self._remote_obj = spec
            return self._remote_obj

    @staticmethod
    def _count_remote(op: str, outcome: str) -> None:
        _obs.GLOBAL_COUNTERS.inc("spfft_store_remote_total", op=op,
                                 outcome=outcome)

    def _remote_fetch(self, rkey: str,
                      write_through: Optional[str] = None
                      ) -> Optional[bytes]:
        """Read one blob from the remote tier: bytes on a hit, None on
        a miss OR any remote failure (the tier is best-effort — a
        wedged object store degrades to a local miss, counted, never
        raised through a plan load). A hit writes through to the local
        path so the next load is a disk read."""
        remote = self._remote_tier()
        if remote is None:
            return None
        try:
            data = remote.get(rkey)
        except BlobStoreError:
            self._count_remote("get", "error")
            return None
        if data is None:
            self._count_remote("get", "miss")
            return None
        self._count_remote("get", "hit")
        if write_through is not None and not self.degraded:
            try:
                self._atomic_write(write_through, data)
            except Exception:
                pass  # the local tier is sick; the bytes still serve
        return data

    def _remote_publish(self, rkey: str, data: bytes) -> None:
        """Best-effort put into the remote tier (the write-behind half
        of a spill): a failure is a counter, never a failed spill."""
        remote = self._remote_tier()
        if remote is None:
            return
        try:
            remote.put(rkey, data)
        except BlobStoreError:
            self._count_remote("put", "error")
            return
        self._count_remote("put", "ok")

    # -- reading -----------------------------------------------------------
    def _read_artifact(self, key: str):
        path = self.artifact_path(key)

        def read():
            with open(path, "rb") as f:
                return f.read()

        try:
            self._check("store.load")
            data = self._retry_io("read", read)
        except FileNotFoundError:
            # below the disk tier: the fleet's shared artifact set
            data = self._remote_fetch(f"art/{key}.plan",
                                      write_through=path)
            if data is None:
                return None
        except OSError as exc:
            raise StoreReject(REASON_IO, f"cannot read {path}: {exc!r}")
        return parse_artifact(data)

    def load_key(self, key: str, plan_kwargs: Optional[dict] = None,
                 expect_sig: Optional[dict] = None):
        """Load artifact ``key`` into a live plan: ``(signature, plan)``
        on success, ``None`` on a miss or a typed rejection (counted;
        the caller rebuilds). ``expect_sig`` cross-checks the header
        signature against an alias/manifest entry."""
        t0 = time.perf_counter()
        try:
            got = self._read_artifact(key)
            if got is None:
                self._count("miss")
                return None
            header, arrays = got
            if expect_sig is not None \
                    and header.get("signature") != expect_sig:
                raise StoreReject(
                    REASON_DIGEST,
                    "artifact signature differs from the alias that "
                    "named it")
            sig = PlanSignature(**header["signature"])
            kwargs = dict(plan_kwargs or {})
            tabs = _plan_tables_of(header, arrays)
            if kwargs.get("use_pallas") is True \
                    and (tabs.pallas_box is None
                         or tabs.pallas_box.get("dec") is None
                         or tabs.pallas_box.get("cmp") is None):
                # the caller demands kernel tables the artifact lacks —
                # a fresh build would construct them; rebuild instead
                raise StoreReject(
                    REASON_INCOMPATIBLE,
                    "use_pallas=True but the artifact has no kernel "
                    "tables")
            ip = _index_plan_of(header, arrays)
            try:
                plan = restore_plan(ip, tabs, precision=sig.precision,
                                    **kwargs)
                n_aot = _install_aot(plan, header, arrays)
            except StoreReject:
                raise
            except Exception as exc:
                # a parseable-but-poisoned table crashing the restore
                # must degrade to a clean rebuild, never an error the
                # artifact caused (the cold path raises its own typed
                # errors for genuinely invalid requests)
                raise StoreReject(
                    REASON_CORRUPT, f"plan restore failed: {exc!r}")
            self._count("hit")
            _obs.record_compile(
                "store_load", time.perf_counter() - t0, t0,
                key=key[:12], aot_installed=n_aot,
                dims=f"{sig.dim_x}x{sig.dim_y}x{sig.dim_z}",
                precision=sig.precision)
            return sig, plan
        except StoreReject as rej:
            self._count("reject", rej.reason)
            import logging
            logging.getLogger("spfft_tpu").warning(
                "spfft_tpu: plan artifact %s rejected (%s) — "
                "rebuilding from scratch", key[:12], rej)
            return None

    def load_signature(self, sig: PlanSignature,
                       plan_kwargs: Optional[dict] = None):
        """Load by canonical signature (the registry's signature-keyed
        read-through)."""
        return self.load_key(signature_key(sig), plan_kwargs,
                             expect_sig=dataclasses.asdict(sig))

    def load_for_request(self, transform_type, dim_x: int, dim_y: int,
                         dim_z: int, triplets, precision: str,
                         scaling, plan_kwargs: Optional[dict] = None):
        """Resolve a RAW request through its alias: ``(signature,
        plan)`` or ``None``. This is the zero-index-build path — the
        only hashing is over the caller's triplet bytes."""
        rkey = request_key(transform_type, dim_x, dim_y, dim_z,
                           triplets, precision, scaling)
        path = self.request_path(rkey)
        try:
            with open(path) as f:
                alias = json.load(f)
        except FileNotFoundError:
            raw = self._remote_fetch(f"req/{rkey}.json",
                                     write_through=path)
            if raw is None:
                self._count("miss")
                return None
            try:
                alias = json.loads(raw)
            except ValueError:
                self._count("reject", REASON_CORRUPT)
                return None
        except (OSError, ValueError):
            self._count("reject", REASON_CORRUPT)
            return None
        if not isinstance(alias, dict) or alias.get(REQUEST_KEY) != 1 \
                or not isinstance(alias.get("artifact"), str):
            self._count("reject", REASON_CORRUPT)
            return None
        return self.load_key(alias["artifact"], plan_kwargs,
                             expect_sig=alias.get("signature"))

    # -- maintenance -------------------------------------------------------
    def _artifact_files(self) -> List[Tuple[str, float, int]]:
        """(path, mtime, size) for every artifact, oldest first."""
        out = []
        adir = self._dir("artifacts")
        for name in os.listdir(adir):
            if not name.endswith(".plan"):
                continue
            path = os.path.join(adir, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            out.append((path, st.st_mtime, st.st_size))
        out.sort(key=lambda e: e[1])
        return out

    def bytes_in_use(self) -> int:
        return sum(size for _, _, size in self._artifact_files())

    def gc(self, max_bytes: Optional[int] = None,
           keep: Optional[str] = None) -> List[str]:
        """Evict oldest-mtime artifacts until the store fits in
        ``max_bytes`` (default: the configured cap; 0 = unbounded).
        ``keep`` names a key never evicted (the artifact just written).
        Dangling request aliases are swept too. Returns removed keys."""
        cap = self.max_bytes if max_bytes is None else int(max_bytes)
        removed = []
        if cap:
            files = self._artifact_files()
            total = sum(size for _, _, size in files)
            for path, _, size in files:
                if total <= cap:
                    break
                key = os.path.basename(path)[:-len(".plan")]
                if keep is not None and key == keep:
                    continue
                try:
                    os.unlink(path)
                except OSError:
                    continue
                total -= size
                removed.append(key)
                _obs.record_store("evict")
        if removed:
            live = {os.path.basename(p)[:-len(".plan")]
                    for p, _, _ in self._artifact_files()}
            rdir = self._dir("requests")
            for name in os.listdir(rdir):
                path = os.path.join(rdir, name)
                try:
                    with open(path) as f:
                        alias = json.load(f)
                    if alias.get("artifact") not in live:
                        os.unlink(path)
                except (OSError, ValueError):
                    continue
        return removed

    def verify(self) -> List[Dict]:
        """Integrity-check every artifact (full parse including the
        checksum and index-digest recomputation, no plan construction).
        Returns one report row per artifact."""
        rows = []
        for path, _, size in self._artifact_files():
            key = os.path.basename(path)[:-len(".plan")]
            row = {"key": key, "bytes": size, "ok": True}
            try:
                with open(path, "rb") as f:
                    header, _ = parse_artifact(f.read())
                meta = header["meta"]
                row.update({
                    "dims": [meta["dim_x"], meta["dim_y"],
                             meta["dim_z"]],
                    "num_values": meta["num_values"],
                    "precision": meta["precision"],
                    "aot": sorted(meta.get("aot") or ())})
            except StoreReject as rej:
                row.update({"ok": False, "reason": rej.reason,
                            "detail": rej.detail})
            except OSError as exc:
                row.update({"ok": False, "reason": REASON_IO,
                            "detail": repr(exc)})
            rows.append(row)
        return rows

    def manifest(self) -> Dict:
        """The boot-prewarm manifest: every loadable artifact's key and
        canonical signature (recorded by ``python -m
        spfft_tpu.serve.store manifest``; consumed by
        ``PlanRegistry.warmup`` / ``warmup_manifest``)."""
        entries = []
        for path, _, size in self._artifact_files():
            key = os.path.basename(path)[:-len(".plan")]
            try:
                with open(path, "rb") as f:
                    header, _ = parse_artifact(f.read())
            except (StoreReject, OSError):
                continue
            meta = header["meta"]
            entries.append({
                "artifact": key,
                "signature": header["signature"],
                "dims": [meta["dim_x"], meta["dim_y"], meta["dim_z"]],
                "num_values": meta["num_values"],
                "precision": meta["precision"],
                "bytes": size,
                "aot": sorted(meta.get("aot") or ()),
            })
        return {MANIFEST_KEY: MANIFEST_VERSION, "store": self.root,
                "entries": entries}

    def write_manifest(self, path: str) -> Dict:
        m = self.manifest()
        self._atomic_write(path, json.dumps(m, indent=2).encode())
        return m

    def append_manifest_entry(self, path: str, entry: Dict) -> Dict:
        """Merge one entry into the live boot-prewarm manifest at
        ``path``: read (a missing file starts a fresh manifest),
        validate, dedupe on the artifact key (last write wins), atomic
        replace. In-process appenders serialize on the module-wide
        ``_MANIFEST_LOCK``; torn reads are impossible by the temp-file
        + ``os.replace`` write contract. An existing-but-invalid file
        raises ``InvalidParameterError`` rather than being clobbered.
        Returns the merged payload."""
        with _MANIFEST_LOCK:
            if os.path.exists(path):
                payload = load_manifest(path)
            else:
                payload = {MANIFEST_KEY: MANIFEST_VERSION,
                           "store": self.root, "entries": []}
            entries = [e for e in payload.get("entries", ())
                       if e.get("artifact") != entry.get("artifact")]
            entries.append(dict(entry))
            payload["entries"] = entries
            self._atomic_write(
                path, json.dumps(payload, indent=2).encode())
        return payload


# -- process-default store resolution ----------------------------------------
_DEFAULT_STORES: Dict[str, PlanArtifactStore] = {}  #: guarded by _DEFAULT_LOCK
_DEFAULT_LOCK = threading.Lock()


def default_store() -> Optional[PlanArtifactStore]:
    """The process-default store every ``PlanRegistry`` attaches when
    no explicit one is given: the control plane's ``plan_store_path``
    (boot artifact) or the ``SPFFT_TPU_PLAN_STORE`` env var; ``None``
    (the default) disables the disk tier. One store object per path."""
    from ..control.config import global_config
    path = global_config().plan_store_path \
        or os.environ.get(PLAN_STORE_ENV) or ""
    if not path:
        return None
    with _DEFAULT_LOCK:
        store = _DEFAULT_STORES.get(path)
        if store is None:
            store = _DEFAULT_STORES[path] = PlanArtifactStore(path)
        return store


def load_manifest(path: str) -> Dict:
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, ValueError) as exc:
        raise InvalidParameterError(
            f"cannot read plan manifest {path!r}: {exc}")
    if not isinstance(payload, dict) \
            or payload.get(MANIFEST_KEY) != MANIFEST_VERSION:
        raise InvalidParameterError(
            f"{path!r} is not a spfft_tpu plan manifest "
            f"(want {MANIFEST_KEY}={MANIFEST_VERSION})")
    return payload


# -- CLI ---------------------------------------------------------------------
def _cli_registry(store: PlanArtifactStore):
    from .registry import PlanRegistry
    return PlanRegistry(store=store)


def _seed_triplets(dim: int, sparsity: float) -> np.ndarray:
    from ..utils.workloads import (sort_triplets_stick_major,
                                   spherical_cutoff_triplets)
    radius = max(1, int((dim // 2) * min(max(sparsity, 0.01), 1.0)))
    tr = spherical_cutoff_triplets(dim, radius)
    return sort_triplets_stick_major(tr, (dim, dim, dim))


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m spfft_tpu.serve.store",
        description="Persistent plan-artifact store maintenance "
                    "(docs/artifact_cache.md)")
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("seed", help="build one canonical workload into "
                                    "the store (cold half of the smoke)")
    s.add_argument("root")
    s.add_argument("--dim", type=int, default=24)
    s.add_argument("--transform", choices=["c2c", "r2c"], default="c2c")
    s.add_argument("--sparsity", type=float, default=0.5,
                   help="cutoff radius as a fraction of dim//2")
    s.add_argument("--precision", choices=["single", "double"],
                   default="single")
    s.add_argument("--reference", action="store_true",
                   help="record a backward-execution reference for "
                        "cross-process bit-exactness checks")
    s.add_argument("--use-pallas", action="store_true",
                   help="build the Pallas compression tables too "
                        "(TPU auto-threshold behavior, forced — the "
                        "expensive cold-start half the artifact then "
                        "persists)")
    s.add_argument("--json", action="store_true")

    m = sub.add_parser("manifest", help="record the store's signatures "
                                        "for boot prewarm")
    m.add_argument("root")
    m.add_argument("-o", "--output", default=None)

    w = sub.add_parser("prewarm", help="warm-load every artifact into a "
                                       "fresh registry")
    w.add_argument("root")
    w.add_argument("--manifest", default=None,
                   help="prewarm only the manifest's signatures "
                        "(default: everything in the store)")
    w.add_argument("--compile", action="store_true",
                   help="also execute one zero-valued backward per "
                        "plan (full executable warmup)")
    w.add_argument("--check-reference", action="store_true",
                   help="re-resolve the seeded reference request and "
                        "assert builds==0 + bit-exact output")
    w.add_argument("--strict", action="store_true",
                   help="exit 1 when any artifact fails to load")
    w.add_argument("--json", action="store_true")

    g = sub.add_parser("gc", help="evict oldest artifacts past the cap")
    g.add_argument("root")
    g.add_argument("--max-bytes", type=int, default=None)
    g.add_argument("--remote", action="store_true",
                   help="also sweep the remote blob tier's req/ "
                        "journal to the blob_store_max_bytes knob "
                        "(or --max-bytes when given)")

    v = sub.add_parser("verify", help="integrity-check every artifact")
    v.add_argument("root")
    v.add_argument("--json", action="store_true")

    args = p.parse_args(argv)
    store = PlanArtifactStore(args.root)

    if args.cmd == "seed":
        from ..serve.registry import PlanRegistry
        reg = PlanRegistry(store=store)
        tr = _seed_triplets(args.dim, args.sparsity)
        ttype = TransformType(args.transform)
        if ttype == TransformType.R2C:
            tr = tr[tr[:, 0] >= 0]
        kwargs = {"use_pallas": True} if args.use_pallas else {}
        t0 = time.perf_counter()
        sig, plan = reg.get_or_build(ttype, args.dim, args.dim,
                                     args.dim, tr,
                                     precision=args.precision, **kwargs)
        plan._finalize()   # cold pays the whole background table build
        cold_ms = (time.perf_counter() - t0) * 1e3
        store.drain()
        rng = np.random.default_rng(20260804)
        vals = rng.standard_normal(
            (plan.index_plan.num_values, 2)).astype(np.float32)
        out = np.asarray(plan.backward(vals))
        if args.reference:
            buf = io.BytesIO()
            np.savez(buf, triplets=tr, values=vals, backward=out,
                     dim=np.int64(args.dim))
            ref = {"transform": args.transform,
                   "precision": args.precision,
                   "artifact": signature_key(sig)}
            store._atomic_write(os.path.join(store.root,
                                             "reference.npz"),
                               buf.getvalue())
            store._atomic_write(os.path.join(store.root,
                                             "reference.json"),
                               json.dumps(ref).encode())
        report = {"cmd": "seed", "cold_resolve_ms": round(cold_ms, 3),
                  "num_values": plan.index_plan.num_values,
                  "builds": reg.stats()["builds"],
                  "store": store.stats()}
        print(json.dumps(report) if args.json
              else json.dumps(report, indent=2))
        return 0

    if args.cmd == "manifest":
        out_path = args.output or os.path.join(args.root,
                                               "manifest.json")
        m = store.write_manifest(out_path)
        print(json.dumps({"cmd": "manifest", "path": out_path,
                          "entries": len(m["entries"])}))
        return 0

    if args.cmd == "prewarm":
        from ..serve.registry import PlanRegistry
        reg = PlanRegistry(store=store)
        # counter DELTAS across the prewarm (the registry is usually
        # the process's first, but in-process callers — tests — may
        # carry prior compile events)
        kinds = ("registry_build", "compression_tables", "store_load")
        base = {kind: _obs.GLOBAL_COUNTERS.get(
            "spfft_compile_events_total", kind=kind) for kind in kinds}
        t0 = time.perf_counter()
        if args.manifest:
            sigs = reg.warmup_manifest(args.manifest,
                                       compile=args.compile,
                                       strict=args.strict)
        else:
            entries = store.manifest()["entries"]
            sigs = reg.warmup(entries, compile=args.compile,
                              strict=args.strict)
        warm_ms = (time.perf_counter() - t0) * 1e3
        stats = reg.stats()
        compile_kinds = {
            kind: _obs.GLOBAL_COUNTERS.get(
                "spfft_compile_events_total", kind=kind) - base[kind]
            for kind in kinds}
        report = {"cmd": "prewarm", "loaded": len(sigs),
                  "warm_resolve_ms": round(warm_ms, 3),
                  "builds": stats["builds"],
                  "store": store.stats(),
                  "compile_events": compile_kinds}
        ok = True
        if args.check_reference:
            ref_path = os.path.join(store.root, "reference.npz")
            meta_path = os.path.join(store.root, "reference.json")
            with open(meta_path) as f:
                ref_meta = json.load(f)
            with np.load(ref_path) as z:
                tr, vals, want = (z["triplets"], z["values"],
                                  z["backward"])
                dim = int(z["dim"])
            t1 = time.perf_counter()
            sig, plan = reg.get_or_build(
                TransformType(ref_meta["transform"]), dim, dim, dim,
                tr, precision=ref_meta["precision"])
            report["reference_resolve_ms"] = round(
                (time.perf_counter() - t1) * 1e3, 3)
            got = np.asarray(plan.backward(vals))
            report["reference_bit_exact"] = bool(
                np.array_equal(got, want))
            report["builds"] = reg.stats()["builds"]
            ok = ok and report["reference_bit_exact"] \
                and report["builds"] == 0
        if args.strict:
            ok = ok and report["builds"] == 0 \
                and not store.stats()["rejects"] \
                and len(sigs) > 0
        report["ok"] = bool(ok)
        print(json.dumps(report) if args.json
              else json.dumps(report, indent=2))
        return 0 if ok else 1

    if args.cmd == "gc":
        removed = store.gc(max_bytes=args.max_bytes)
        report = {"cmd": "gc", "removed": removed,
                  "bytes_in_use": store.bytes_in_use()}
        if args.remote:
            from ..control.config import global_config
            from ..net.blobstore import gc_blobstore
            tier = store._remote_tier()
            if tier is None:
                report["remote"] = {"error": "no remote blob tier "
                                             "configured"}
            else:
                cap = args.max_bytes if args.max_bytes is not None \
                    else global_config().blob_store_max_bytes
                report["remote"] = gc_blobstore(tier, cap)
        print(json.dumps(report))
        return 0

    if args.cmd == "verify":
        rows = store.verify()
        bad = [r for r in rows if not r["ok"]]
        report = {"cmd": "verify", "artifacts": len(rows),
                  "bad": len(bad), "rows": rows}
        print(json.dumps(report) if args.json
              else json.dumps(report, indent=2))
        return 0 if not bad else 1

    return 2


if __name__ == "__main__":  # pragma: no cover - exercised by CLI tests
    raise SystemExit(main())

"""Pod-scale multi-host serving: the :class:`PodFrontend`.

The reference library's execution tier is multi-rank from the ground up
(slab/pencil decomposition over an MPI communicator); until this round
the serving layer covered exactly one process's devices —
``ServeExecutor.submit`` rejected ``DistributedTransformPlan`` at the
door. This module is the scale-out tier that turns per-host throughput
into pod throughput:

* **Host lanes** — each :class:`HostLane` wraps one per-host
  ``ServeExecutor`` behind a transport seam (:class:`LoopbackTransport`
  for the in-process emulation tier-1 runs on CPU; a real pod swaps in
  an RPC transport with the same surface). Lanes are *reconciled* at
  frontend construction over the digest-validation path in
  ``parallel.multihost``: every host must hold the same
  ``PlanSignature`` set and, for distributed plans, the same 16-byte
  plan fingerprint — anything else is a typed
  ``ClusterReconciliationError`` (the serving-tier mirror of the
  reference's cross-rank parameter checks).
* **Routing by plan type** — single-device requests go to the
  least-loaded host via power-of-two-choices over live
  ``ServeMetrics.signals()`` (queue depth x device-execute p50,
  refreshed per dispatch); ``DistributedTransformPlan`` requests are
  handed to the pod-wide SPMD lane, which serializes per-signature onto
  the plan's shard_map executables — so
  ``DistributedPlanUnsupportedError`` is no longer the frontend
  submit-path answer (it remains the bare single-host executor's).
* **Federated telemetry** — trace contexts propagate across the host
  boundary (``obs.TraceContext``: the frontend's ``cluster.request``
  span is the parent, each host lane's ``serve.request`` root is its
  child, one trace id end-to-end), and :meth:`PodFrontend.metrics_text`
  merges every host's Prometheus exposition into one pod-level
  ``/metrics`` (each host's series re-labelled ``host="..."``), with
  :meth:`PodFrontend.health` as the worst-health-wins ``/healthz``.
* **Fault sites** — ``cluster.route`` (the host pick),
  ``cluster.rpc`` (every lane RPC), ``cluster.reconcile`` (the
  per-host digest collective) and ``cluster.readmit`` (the
  resurrection re-reconcile) extend the package seam in
  ``spfft_tpu.faults``; a lane whose transport fails is marked dead,
  the pod degrades, survivors keep serving and every issued future
  still resolves.
* **Self-healing membership** (round 21) — the frontend stamps every
  routed request with the membership view epoch from
  :mod:`spfft_tpu.net.membership` (a private ``ViewCoordinator`` for
  loopback pods, the agents' lease-based coordinator for remote ones).
  Work stamped with an older epoch is rejected typed
  (``StaleEpochError``, transient): the frontend refetches the view
  and retries, so two frontends over the same pod converge on one
  membership instead of disagreeing silently. A dead lane is no longer
  dead forever: it enters a backoff-probed resurrection ladder
  (``rpc_health`` probes under exponential backoff + jitter), is
  RE-RECONCILED against an incumbent (the round-18 fingerprint digest
  — a resurrected host serving stale plans is blocked, not readmitted)
  and only then readmitted warm with an epoch bump.

``python -m spfft_tpu.serve.cluster --smoke`` is the deterministic
2-host CPU smoke behind ``make cluster-smoke``; ``--simulate`` runs the
scripted skewed-load routing scenario recorded in BENCHMARKS.md
Round-18. See docs/cluster.md.
"""

from __future__ import annotations

import heapq
import math
import random
import threading
import time
import zlib
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import faults as _faults
from .. import obs as _obs
from ..errors import (ClusterError, ClusterReconciliationError,
                      DeadlineExpiredError, HostLaneError,
                      InvalidParameterError, NetAuthError,
                      ParameterMismatchError, PlanArtifactError,
                      QueueFullError, StaleEpochError)
from ..faults import InjectedFault
from ..obs.counters import METRIC_SPECS
from ..obs.exporters import _PromBuilder, parse_prometheus_text, \
    prometheus_text
from ..parallel.multihost import plan_fingerprint, validate_consistent
from ..plan import TransformPlan
from ..types import Scaling
from .executor import ServeExecutor
from .registry import PlanSignature

#: Lifecycle states ordered bad-to-worse; the pod's aggregate health is
#: the worst ALIVE lane's state, floored at "degraded" while any lane
#: is dead, and "failed" only once no lane is alive.
_STATE_ORDER = ("healthy", "degraded", "draining", "failed")
_STATE_RANK = {s: i for i, s in enumerate(_STATE_ORDER)}

_PRIORITIES = ("normal", "high")

#: Resurrection-ladder backoff growth cap: a probed-forever lane
#: settles at ``lane_probe_backoff * 64`` between probes, never more.
_PROBE_BACKOFF_CAP = 64

#: Metric families that belong to one LANE's executor (per-lane
#: ``ServeMetrics`` / ``PlanRegistry`` facts): the only families an
#: IN-PROCESS lane contributes to the federated pod exposition.
#: Everything else an in-process lane renders — compile, faults, SLO,
#: store, cluster, membership, recorder, timing, trace — reads this
#: process's shared globals, which :meth:`PodFrontend.metrics_text`
#: renders exactly once; re-exporting them per lane duplicated every
#: process-wide series under per-lane ``host`` labels, with the
#: surviving copy dependent on lane iteration order.
_LANE_LEVEL_FAMILIES = ("spfft_serve_", "spfft_registry_")


def _membership_module():
    """Deferred import of :mod:`spfft_tpu.net.membership` —
    ``net.transport`` imports THIS module at its top level, so the
    membership plane must resolve lazily to keep the package acyclic."""
    from ..net import membership
    return membership


def load_score(signals: dict) -> Tuple[float, float, float]:
    """The routing load of one host from its live
    ``ServeMetrics.signals()``: expected queue drain time (queue depth x
    device-execute p50) plus the measured wire round-trip to reach the
    host (``wire_rtt``, merged in by ``net.TcpHostLane.rpc_signals``;
    0 for in-process lanes), tie-broken by raw depth then raw p50.
    Small is idle. A host with no execute history yet scores by wire
    distance and depth alone — two cold in-process hosts compare equal
    and the sampler's order decides."""
    depth = float(signals.get("queue_depth", 0) or 0)
    dx50 = float(signals.get("device_execute_p50", 0.0) or 0.0)
    rtt = float(signals.get("wire_rtt", 0.0) or 0.0)
    return (depth * max(dx50, 1e-6) + rtt, depth, dx50)


class LoopbackTransport:
    """The in-process host-boundary seam. Every lane RPC funnels
    through :meth:`check`, which consults the package ``cluster.rpc``
    fault site and the lane's liveness — exactly where a real pod's
    RPC stub would surface connection errors. A failing check raises
    the typed, transient :class:`HostLaneError` the frontend's
    route-around handling keys on."""

    def __init__(self, host: str):
        self.host = host
        self.alive = True

    def check(self, op: str) -> None:
        _obs.GLOBAL_COUNTERS.inc("spfft_cluster_rpcs_total",
                                 host=self.host, op=op)
        if not self.alive:
            _obs.GLOBAL_COUNTERS.inc("spfft_cluster_rpc_failures_total",
                                     host=self.host, op=op)
            raise HostLaneError(
                f"host lane {self.host!r} is dead (transport down)",
                host=self.host)
        try:
            _faults.check_site("cluster.rpc")
        except InjectedFault as exc:
            _obs.GLOBAL_COUNTERS.inc("spfft_cluster_rpc_failures_total",
                                     host=self.host, op=op)
            raise HostLaneError(
                f"host lane {self.host!r} RPC {op!r} failed: {exc}",
                host=self.host) from exc


class HostLane:
    """One per-host serving lane: a host descriptor, its
    ``ServeExecutor`` and the transport the frontend reaches it
    through. The ``rpc_*`` surface is the complete host boundary — a
    real multi-process pod implements exactly these five calls over its
    RPC layer; the emulation calls them in-process behind the
    ``cluster.rpc`` fault seam."""

    def __init__(self, host: str, executor: ServeExecutor,
                 transport: Optional[LoopbackTransport] = None):
        self.host = host
        self.executor = executor
        self.transport = transport or LoopbackTransport(host)
        # set by PodFrontend.leave(): a draining lane finishes its
        # queue but receives no new routes
        self.draining = False

    @property
    def alive(self) -> bool:
        return self.transport.alive

    # trace: boundary(ctx)
    def rpc_submit(self, signature: PlanSignature, values,
                   kind: str = "backward",
                   scaling: Scaling = Scaling.NONE,
                   timeout: Optional[float] = None,
                   priority: str = "normal", ctx=None,
                   epoch: Optional[int] = None) -> Future:
        """Submit one single-device request to this host's executor,
        restoring the propagated trace context so the host's
        ``serve.request`` root is a child of the frontend span. The
        ``epoch`` stamp is accepted for surface parity with the remote
        lane but not fenced here: an in-process pod fences at the
        frontend's door (``PodFrontend.submit``), where the one shared
        ``ViewCoordinator`` lives."""
        self.transport.check("submit")
        return self.executor.submit(signature, values, kind,
                                    scaling=scaling, timeout=timeout,
                                    priority=priority, trace_ctx=ctx)

    def rpc_signals(self) -> dict:
        """Live ``ServeMetrics.signals()`` — the routing input."""
        self.transport.check("signals")
        return self.executor.metrics.signals()

    def rpc_signatures(self) -> List[PlanSignature]:
        """The registry's signature set — the reconciliation input."""
        self.transport.check("signatures")
        return self.executor.registry.signatures()

    def rpc_plan(self, signature: PlanSignature):
        """The plan object behind ``signature`` (None if unheld)."""
        self.transport.check("plan")
        return self.executor.registry.get(signature)

    def rpc_metrics_text(self) -> str:
        """This host's full Prometheus exposition — what its own
        ``MetricsServer`` would serve; the federation input."""
        self.transport.check("metrics")
        return prometheus_text(metrics=self.executor.metrics,
                               registry=self.executor.registry)

    def rpc_health(self) -> dict:
        """This host's executor ``health()`` snapshot."""
        self.transport.check("health")
        return self.executor.health()

    def rpc_prewarm(self, signatures, strict: bool = True) -> int:
        """Pull a signature set warm through this host's artifact
        tiers — the joining-lane half of elastic membership."""
        self.transport.check("prewarm")
        return self.executor.registry.prewarm_signatures(
            list(signatures), strict=strict)

    def rpc_drain(self) -> None:
        """Drain this host's queue to completion — the leaving-lane
        half of elastic membership."""
        self.transport.check("drain")
        self.executor.close(drain=True)

    def rpc_stats(self) -> dict:
        """This host's registry ``stats()`` (the warm-boot
        observable)."""
        self.transport.check("stats")
        return self.executor.registry.stats()

    def rpc_incident(self, reason: str) -> dict:
        """This host's flight-recorder incident bundle, built in
        memory — the caller owns persistence (a pod capture writes
        ONE file). In-process lanes share the process's journal, so
        :meth:`PodFrontend.capture_incident` asks only remote lanes;
        the verb exists here for surface parity with the agent."""
        self.transport.check("incident")
        from ..obs.recorder import build_incident_bundle
        return build_incident_bundle(reason, host=self.host)


class _SPMDRequest:
    """One queued distributed request inside the coalescer."""

    __slots__ = ("plan", "values", "root", "deadline", "priority",
                 "future")

    def __init__(self, plan, values, root, deadline, priority):
        self.plan = plan
        self.values = values
        self.root = root
        self.deadline = deadline
        self.priority = priority
        self.future: Future = Future()


class SPMDCoalescer:
    """The pod-wide distributed lane, grown into a coalescing
    scheduler: N queued same-signature distributed requests drain into
    ONE batched SPMD execution whose exchange moves all N payloads in a
    single collective round (the reference's shared-``Grid``
    amortization, resurrected for the pod — the distributed twin of the
    executor's fused batching win).

    Requests queue per ``(signature, kind, scaling)`` key in EDF order
    (high priority first, then earliest deadline, then arrival). A
    per-key drainer waits out a ``spmd_batch_window``-long batching
    window — closed EARLY when a queued deadline would lapse inside it
    or a high-priority member is already aboard — then executes up to
    ``spmd_max_batch`` requests through the plan's
    ``coalesce_backward``/``coalesce_forward`` batched entry points and
    demuxes per-request results. Plans without batched entry points
    (and comm-size-1 delegates, and windows that close with a single
    member) fall back to the per-request serial path, so coalescing is
    strictly an optimization: every interleaving is bit-exact vs serial
    execution.

    Admission is unchanged from the round-19 lane: the queue is bounded
    by the ``max_queue`` knob (typed ``QueueFullError``), and expired
    deadlines purge as ``DeadlineExpiredError`` — now also at
    window-drain time, so a request that dies while queued never rides
    a collective round."""

    #: bound on the launch-duration reservoir feeding signals()
    _RESERVOIR = 256

    def __init__(self, max_workers: int = 2,
                 span_args: Optional[dict] = None):
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="spfft-pod-spmd")
        self._cv = threading.Condition()
        self._queues: Dict[tuple, list] = {}  #: guarded by _cv
        self._active: set = set()  #: guarded by _cv
        self._depth = 0  #: guarded by _cv
        self._seq = 0  #: guarded by _cv
        self._closed = False  #: guarded by _cv
        self._launches = 0  #: guarded by _cv
        self._coalesced = 0  #: guarded by _cv
        self._batch_hist: Dict[int, int] = {}  #: guarded by _cv
        self._launch_s: List[float] = []  #: guarded by _cv
        self._span_args = dict(span_args or {})

    # -- admission ----------------------------------------------------------
    def submit(self, signature: PlanSignature, plan, values, kind: str,
               scaling: Scaling, root,
               timeout: Optional[float] = None,
               priority: str = "normal") -> Future:
        """Admission-controlled enqueue: the lane's queue is bounded by
        the control plane's ``max_queue`` knob (overflow is the same
        typed ``QueueFullError`` backpressure the single-host executor
        answers), and a request carrying a deadline that expires while
        queued is purged as ``DeadlineExpiredError`` instead of burning
        the whole mesh on an answer nobody awaits."""
        from ..control.config import global_config
        cap = int(global_config().max_queue)
        deadline = None if timeout is None \
            else time.monotonic() + float(timeout)
        req = _SPMDRequest(plan, values, root, deadline, priority)
        key = (signature, kind, Scaling(scaling))
        with self._cv:
            if self._closed:
                raise ClusterError("pod SPMD lane is closed")
            if self._depth >= cap:
                _obs.GLOBAL_COUNTERS.inc(
                    "spfft_cluster_spmd_rejected_total",
                    reason="queue_full")
                raise QueueFullError(
                    f"pod SPMD lane queue is full ({cap})")
            self._depth += 1
            self._seq += 1
            rank = (0 if priority == "high" else 1,
                    math.inf if deadline is None else deadline,
                    self._seq)
            heapq.heappush(self._queues.setdefault(key, []),
                           rank + (req,))
            if key not in self._active:
                self._active.add(key)
                self._pool.submit(self._drain_key, key)
            self._cv.notify_all()
        return req.future

    # -- the drain loop -----------------------------------------------------
    def _drain_key(self, key) -> None:
        """Form and execute coalescing rounds for one key until its
        queue is dry. Between rounds the drainer hands its pool slot
        back (resubmitting itself) so other signatures' drainers get a
        turn under a small pool."""
        while True:
            bucket = self._collect(key)
            if bucket:
                self._execute_round(key, bucket)
            with self._cv:
                if not self._queues.get(key):
                    self._active.discard(key)
                    self._queues.pop(key, None)
                    return
                if not self._closed:
                    try:
                        self._pool.submit(self._drain_key, key)
                        return
                    except RuntimeError:  # pragma: no cover
                        pass  # pool shutting down: finish inline

    def _collect(self, key) -> List[_SPMDRequest]:
        """Wait out the batching window, absorbing same-key arrivals
        until the bucket is full or the window closes (early on an
        imminent member deadline or a high-priority member). Expired
        queued requests purge here — the drain-time half of the
        deadline contract."""
        from ..control.config import global_config
        cfg = global_config()
        window = float(cfg.spmd_batch_window)
        cap = max(1, int(cfg.spmd_max_batch))
        bucket: List[_SPMDRequest] = []
        purged: List[_SPMDRequest] = []
        until = None
        with self._cv:
            while True:
                now = time.monotonic()
                lane = self._queues.get(key) or []
                expired = [e for e in lane if e[1] <= now]
                if expired:
                    lane[:] = [e for e in lane if e[1] > now]
                    heapq.heapify(lane)
                    purged.extend(e[3] for e in expired)
                    self._depth -= len(expired)
                while lane and len(bucket) < cap:
                    bucket.append(heapq.heappop(lane)[3])
                if len(bucket) >= cap or self._closed or not bucket:
                    break
                if until is None:
                    until = now + window
                close_at = min(until,
                               min((r.deadline for r in bucket
                                    if r.deadline is not None),
                                   default=math.inf))
                if close_at - now <= 0 \
                        or any(r.priority == "high" for r in bucket):
                    break
                self._cv.wait(close_at - now)
        # purged futures resolve OUTSIDE the lock (done callbacks run
        # arbitrary frontend code)
        for req in purged:
            _obs.GLOBAL_COUNTERS.inc("spfft_cluster_spmd_rejected_total",
                                     reason="expired")
            req.future.set_exception(DeadlineExpiredError(
                "distributed request deadline expired in the SPMD "
                "lane queue"))
        return bucket

    # -- one coalesced round ------------------------------------------------
    def _execute_round(self, key, bucket: List[_SPMDRequest]) -> None:
        signature, kind, scaling = key
        batch = len(bucket)
        _obs.GLOBAL_COUNTERS.inc("spfft_cluster_spmd_requests_total",
                                 batch)
        span = None
        traced = [r for r in bucket if r.root is not None]
        if traced and _obs.active():
            first = traced[0].root
            args = {"kind": kind, "batch": batch,
                    "member_trace_ids": [r.root.trace_id
                                         for r in traced]}
            args.update(self._span_args)
            # span: closed-by(SPMDCoalescer._execute_round)
            span = _obs.GLOBAL_TRACER.begin(
                "cluster.spmd_execute", cat="cluster",
                trace_id=first.trace_id, parent=first,
                track="pod:spmd", args=args)
        t0 = time.perf_counter()
        try:
            _faults.check_site("cluster.spmd_window")
            results = self._execute(bucket[0].plan,
                                    [r.values for r in bucket],
                                    kind, scaling)
        except BaseException as exc:
            if span is not None:
                _obs.GLOBAL_TRACER.finish(span, status="error",
                                          error=type(exc).__name__)
            self._finish_round(batch, time.perf_counter() - t0)
            for req in bucket:
                req.future.set_exception(exc)
            return
        if span is not None:
            _obs.GLOBAL_TRACER.finish(span)
        self._finish_round(batch, time.perf_counter() - t0)
        if batch > 1:
            _obs.GLOBAL_COUNTERS.inc("spfft_cluster_spmd_coalesced_total",
                                     batch)
        _obs.GLOBAL_COUNTERS.inc("spfft_cluster_spmd_batch_size_total",
                                 size=str(batch))
        for req, result in zip(bucket, results):
            req.future.set_result(result)

    def _finish_round(self, batch: int, seconds: float) -> None:
        with self._cv:
            self._depth -= batch
            self._launches += 1
            self._batch_hist[batch] = self._batch_hist.get(batch, 0) + 1
            if batch > 1:
                self._coalesced += batch
            self._launch_s.append(seconds)
            del self._launch_s[:-self._RESERVOIR]

    @staticmethod
    def _execute(plan, values_list, kind, scaling):
        """Batched execution when the plan offers it; the per-request
        serial path otherwise (duck-typed test plans, remote
        descriptors). ``coalesce_*`` itself serializes batch==1 and
        comm-size-1 delegates, so this seam is bit-exactness-neutral."""
        if kind == "backward":
            coalesce = getattr(plan, "coalesce_backward", None)
            if coalesce is not None:
                return coalesce(values_list)
            return [plan.backward(v) for v in values_list]
        coalesce = getattr(plan, "coalesce_forward", None)
        if coalesce is not None:
            return coalesce(values_list, scaling)
        return [plan.forward(v, scaling) for v in values_list]

    # -- telemetry ----------------------------------------------------------
    def signals(self) -> dict:
        """Live coalescer signals for the controller's
        ``spmd_batch_window``/``spmd_max_batch`` rule."""
        with self._cv:
            depth = self._depth
            launches = self._launches
            coalesced = self._coalesced
            hist = dict(self._batch_hist)
            samples = sorted(self._launch_s)
        p50 = samples[len(samples) // 2] if samples else 0.0
        return {"spmd_queue_depth": depth, "spmd_launches": launches,
                "spmd_coalesced": coalesced, "spmd_launch_p50": p50,
                "spmd_batch_hist": hist}

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._pool.shutdown(wait=True)


#: Back-compat name for the round-19 lane the coalescer grew out of.
_SPMDLane = SPMDCoalescer


class PodFrontend:
    """N host lanes + one pod-wide SPMD lane behind a single
    ``submit()``.

    ``lanes`` is a sequence of :class:`HostLane` (or ``(host, executor)``
    pairs). Construction RECONCILES the pod (see :meth:`reconcile`) —
    a frontend never starts routing onto hosts that disagree about the
    plan set. ``policy`` is ``"p2c"`` (power-of-two-choices, default)
    or ``"rr"`` (round-robin; kept for the routing benchmark and as the
    degenerate fallback). ``seed`` fixes the choice sampler, so a
    replayed trace routes identically.

    ``membership`` is the :class:`net.membership.ViewCoordinator` this
    frontend fences against: None builds a private one (a loopback pod
    is trivially its own coordinator); two frontends over the same
    lanes share one coordinator to converge on a single epoch-fenced
    view. When any lane is remote (it carries ``rpc_view``), the
    AGENTS' lease-based coordinator is the authority instead and the
    local coordinator is only this frontend's fencing mirror.
    """

    def __init__(self, lanes: Sequence, policy: str = "p2c",
                 seed: int = 0, reconcile: bool = True,
                 membership=None):
        if policy not in ("p2c", "rr"):
            raise InvalidParameterError(
                f"routing policy must be 'p2c' or 'rr', got {policy!r}")
        self._lanes: List[HostLane] = []
        for lane in lanes:
            if isinstance(lane, HostLane):
                self._lanes.append(lane)
            else:
                host, executor = lane
                self._lanes.append(HostLane(host, executor))
        if not self._lanes:
            raise InvalidParameterError("a pod needs at least one lane")
        names = [ln.host for ln in self._lanes]
        if len(set(names)) != len(names):
            raise InvalidParameterError(
                f"duplicate host names in pod: {names}")
        self.policy = policy
        self._rng = random.Random(seed)  #: guarded by _rng_lock
        self._rng_lock = threading.Lock()
        self._rr_next = 0  #: guarded by _rng_lock
        self._spmd = _SPMDLane()
        self._tracer = _obs.GLOBAL_TRACER
        self._closed = False
        # -- membership plane: the epoch this frontend fences against
        self._remote = any(hasattr(ln, "rpc_view") for ln in self._lanes)
        if membership is None:
            membership = _membership_module().ViewCoordinator(
                min(names))
        self._membership = membership
        for ln in self._lanes:
            self._membership.ensure(ln.host)
        #: resurrection ladder: host -> [failed probes, next-probe
        #: deadline (monotonic)]  #: guarded by _dead_lock
        self._dead: Dict[str, list] = {}
        self._dead_lock = threading.Lock()
        #: hosts with a probe in flight (background worker or an
        #: explicit probe_dead walk) — one prober per host at a time
        #: guarded by _dead_lock
        self._probing: set = set()
        #: background prober: routing only SCHEDULES due probes here —
        #: the health RPC and the strict prewarm + re-reconcile
        #: readmission gate (which may compile plans) must never run
        #: inline on a live submit
        self._probe_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="spfft-pod-probe")
        self._stamp = self._membership.epoch  # refreshed via view()
        if self._remote:
            try:
                self.view()
            except (ClusterError, HostLaneError):
                pass  # no agent reachable yet; first submit refetches
        if reconcile:
            self.reconcile()
        # flight recorder: route auto triggers (SLO page, health
        # degrade, lane death) through the POD capture, so one rising
        # edge snapshots every alive host, not just this process
        from ..obs import recorder as _recorder
        self._incident_capturer = self.capture_incident
        _recorder.set_incident_capturer(self._incident_capturer)
        _recorder.set_health_provider(self.health)

    # -- reconciliation -----------------------------------------------------
    def reconcile(self) -> None:
        """Verify every alive lane agrees on the plan set: identical
        ``PlanSignature`` sets, and for each distributed plan an
        identical ``parallel.multihost`` fingerprint, checked through
        ``validate_consistent`` with a loopback collective per host
        (the ``cluster.reconcile`` fault site fires once per host per
        plan, where a real pod's allgather would run). Raises
        :class:`ClusterReconciliationError` naming the disagreement."""
        lanes = [ln for ln in self._lanes if ln.alive]
        if not lanes:
            raise ClusterError("no alive host lanes to reconcile")
        try:
            sig_sets = [ln.rpc_signatures() for ln in lanes]
        except HostLaneError as exc:
            self._count_reconcile("failed")
            raise ClusterReconciliationError(
                f"reconciliation RPC failed: {exc}") from exc
        base = set(sig_sets[0])
        for ln, sigs in zip(lanes[1:], sig_sets[1:]):
            if set(sigs) != base:
                self._count_reconcile("mismatch")
                raise ClusterReconciliationError(
                    f"host {ln.host!r} holds a different plan set than "
                    f"host {lanes[0].host!r}: "
                    f"{sorted(set(sigs) ^ base, key=repr)} differ")
        for sig in sorted(base, key=repr):
            plans = [ln.rpc_plan(sig) for ln in lanes]
            if any(p is None for p in plans):
                self._count_reconcile("mismatch")
                missing = [ln.host for ln, p in zip(lanes, plans)
                           if p is None]
                raise ClusterReconciliationError(
                    f"host(s) {missing} no longer hold {sig}")
            if any(isinstance(p, dict) for p in plans):
                # at least one remote lane: plans never cross the wire,
                # so agreement reduces to descriptor rows
                self._reconcile_descriptors(sig, lanes, plans)
                continue
            if isinstance(plans[0], TransformPlan):
                continue  # local plans: signature equality IS the digest
            rows = [np.frombuffer(plan_fingerprint(p.dist_plan), np.uint8)
                    for p in plans]
            for i, (ln, plan) in enumerate(zip(lanes, plans)):
                try:
                    _faults.check_site("cluster.reconcile")
                    validate_consistent(
                        plan.dist_plan,
                        collective=(_loopback_allgather(rows, i),
                                    len(lanes), i))
                except ParameterMismatchError as exc:
                    self._count_reconcile("mismatch")
                    raise ClusterReconciliationError(
                        f"distributed plan {sig} disagrees across the "
                        f"pod (observed from host {ln.host!r}): {exc}"
                    ) from exc
                except InjectedFault as exc:
                    self._count_reconcile("failed")
                    raise ClusterReconciliationError(
                        f"reconciliation collective failed on host "
                        f"{ln.host!r}: {exc}") from exc
        self._count_reconcile("ok")

    @staticmethod
    def _count_reconcile(outcome: str) -> None:
        _obs.GLOBAL_COUNTERS.inc("spfft_cluster_reconciliations_total",
                                 outcome=outcome)

    def _reconcile_descriptors(self, sig, lanes, plans) -> None:
        """Digest agreement when any lane answers a remote plan
        DESCRIPTOR (``net.TcpHostLane.rpc_plan``): every lane's answer
        — descriptor, local single plan, or local distributed plan —
        reduces to a ``(distributed, fingerprint-hex)`` row and all
        rows must be identical; the wire analogue of the loopback
        fingerprint collective."""
        rows = []
        for lane, p in zip(lanes, plans):
            try:
                _faults.check_site("cluster.reconcile")
            except InjectedFault as exc:
                self._count_reconcile("failed")
                raise ClusterReconciliationError(
                    f"reconciliation failed on host {lane.host!r}: "
                    f"{exc}") from exc
            if isinstance(p, dict):
                rows.append((bool(p.get("distributed")),
                             p.get("fingerprint")))
            elif isinstance(p, TransformPlan):
                rows.append((False, None))
            else:
                rows.append((True, plan_fingerprint(p.dist_plan).hex()))
        if len(set(rows)) != 1:
            self._count_reconcile("mismatch")
            detail = {lane.host: row
                      for lane, row in zip(lanes, rows)}
            raise ClusterReconciliationError(
                f"plan {sig} disagrees across the pod: {detail}")

    # -- membership view ----------------------------------------------------
    @property
    def epoch(self) -> int:
        """The view epoch this frontend currently stamps on routed
        work (the last one :meth:`view` fetched)."""
        return self._stamp

    def view(self) -> dict:
        """Fetch, verify and adopt the pod's current signed membership
        view; returns its wire form and refreshes the fencing stamp.
        Loopback pods serve it from the frontend's own coordinator;
        remote pods fetch it from the first reachable agent (every
        agent converges on the coordinator's view). A view whose
        signature does not verify is the permanent
        :class:`NetAuthError` — never silently adopted."""
        mm = _membership_module()
        if not self._remote:
            v = self._membership.view()
            self._stamp = v.epoch
            return v.to_wire()
        last: Optional[Exception] = None
        for lane in self._lanes:
            if not hasattr(lane, "rpc_view") or not lane.alive:
                continue
            try:
                wire = lane.rpc_view(ctx=None)
            except HostLaneError as exc:
                last = exc
                continue
            v = mm.MembershipView.from_wire(wire)
            if not v.verify(mm._secret()):
                _obs.GLOBAL_COUNTERS.inc(
                    "spfft_membership_views_total", outcome="bad_sig")
                raise NetAuthError(
                    f"membership view from host {lane.host!r} does "
                    f"not verify")
            _obs.GLOBAL_COUNTERS.inc("spfft_membership_views_total",
                                     outcome="adopted")
            self._stamp = v.epoch
            return v.to_wire()
        raise ClusterError(
            "no alive host lane served the membership view"
            + (f" (last transport error: {last})" if last else ""))

    # -- submission ---------------------------------------------------------
    def submit(self, signature: PlanSignature, values,
               kind: str = "backward",
               scaling: Scaling = Scaling.NONE,
               timeout: Optional[float] = None,
               priority: str = "normal") -> Future:
        """Route one request into the pod; returns its Future.

        Single-device signatures go to the least-loaded host
        (power-of-two-choices under the default policy) and retain
        every single-host semantics (deadlines, priorities,
        backpressure — a chosen host's ``QueueFullError`` propagates).
        Distributed signatures execute on the pod-wide SPMD lane.
        Either way the frontend's ``cluster.request`` span is the
        request's trace root and resolves exactly when the future
        does."""
        if self._closed:
            raise ClusterError("pod frontend is closed")
        if kind not in ("backward", "forward"):
            raise InvalidParameterError(
                f"kind must be 'backward' or 'forward', got {kind!r}")
        if priority not in _PRIORITIES:
            raise InvalidParameterError(
                f"priority must be 'normal' or 'high', got {priority!r}")
        scaling = Scaling(scaling)
        if not self._remote:
            # loopback fencing happens at the frontend's own door: a
            # stamp gone stale (another frontend over the shared
            # coordinator changed the membership) is rejected typed —
            # and recovered exactly as the contract says, by refetching
            # the view and retrying with the fresh epoch.
            try:
                self._membership.check_epoch(self._stamp,
                                             node="frontend")
            except StaleEpochError:
                self._stamp = self._membership.epoch
        plan = self._resolve_plan(signature)
        # a dict is a remote plan DESCRIPTOR (net.TcpHostLane.rpc_plan
        # — the plan object itself never crosses the wire): execution
        # happens host-side, so even a distributed descriptor routes
        # through the lane path
        remote = isinstance(plan, dict)
        if remote:
            distributed = bool(plan.get("distributed"))
        else:
            distributed = not isinstance(plan, TransformPlan)
        root = None
        if _obs.active() and self._tracer.sample():
            # span: closed-by(PodFrontend._settle)
            root = self._tracer.begin(
                "cluster.request", cat="cluster",
                trace_id=self._tracer.new_trace_id(), track="pod",
                args={"kind": kind,
                      "plan": "distributed" if distributed else "single"})
        try:
            if distributed and not remote:
                fut = self._spmd.submit(signature, plan, values, kind,
                                        scaling, root, timeout=timeout,
                                        priority=priority)
                _obs.GLOBAL_COUNTERS.inc("spfft_cluster_routed_total",
                                         host="pod", kind="distributed")
            else:
                # remote distributed descriptors route with SIGNATURE
                # AFFINITY: the agent-side coalescing window can only
                # merge what routing co-locates, so concurrent
                # same-signature requests must land on the same host
                fut = self._submit_single(
                    signature, values, kind, scaling, timeout, priority,
                    _obs.span_context(root),
                    routed_kind="distributed" if distributed
                    else "single",
                    affinity=signature if distributed else None)
        except BaseException as exc:
            self._settle(root, exc)
            raise
        fut.add_done_callback(
            lambda f, _root=root: self._settle(_root, f.exception()))
        return fut

    def submit_backward(self, signature, values,
                        timeout: Optional[float] = None,
                        priority: str = "normal") -> Future:
        return self.submit(signature, values, "backward",
                           timeout=timeout, priority=priority)

    def submit_forward(self, signature, space,
                       scaling: Scaling = Scaling.NONE,
                       timeout: Optional[float] = None,
                       priority: str = "normal") -> Future:
        return self.submit(signature, space, "forward", scaling=scaling,
                           timeout=timeout, priority=priority)

    def _settle(self, root, exc: Optional[BaseException]) -> None:
        """The one closer of the frontend's ``cluster.request`` span —
        every resolution path (submit-time raise, future success,
        future failure) funnels through it, which is how the
        zero-unclosed-spans contract extends across the pod."""
        if root is None:
            return
        if exc is None:
            self._tracer.finish(root)
        else:
            self._tracer.finish(root, status="error",
                                error=type(exc).__name__)

    def _resolve_plan(self, signature: PlanSignature):
        """The plan behind ``signature`` from the first alive lane
        (reconciliation guarantees every lane agrees)."""
        last: Optional[HostLaneError] = None
        for lane in self._lanes:
            if not lane.alive:
                continue
            try:
                plan = lane.rpc_plan(signature)
            except HostLaneError as exc:
                self._mark_dead(lane)
                last = exc
                continue
            if plan is None:
                raise InvalidParameterError(
                    f"signature not held by the pod (warm up first): "
                    f"{signature}")
            return plan
        raise ClusterError(
            f"no alive host lanes to resolve {signature}"
            + (f" (last transport error: {last})" if last else ""))

    def _submit_single(self, signature, values, kind, scaling, timeout,
                       priority, ctx,
                       routed_kind: str = "single",
                       affinity=None) -> Future:
        """Pick a host (p2c or rr; signature affinity when given), fail
        over across survivors on transport errors. Backpressure
        (``QueueFullError``) and every other executor-side error
        propagate untranslated — routing only absorbs the
        lane-is-unreachable failure mode."""
        _faults.check_site("cluster.route")
        candidates = (self._candidates() if affinity is None
                      else self._affinity_candidates(affinity))
        for lane in candidates:
            try:
                fut = lane.rpc_submit(signature, values, kind,
                                      scaling=scaling, timeout=timeout,
                                      priority=priority, ctx=ctx,
                                      epoch=self._stamp)
            except HostLaneError:
                self._mark_dead(lane)
                continue
            _obs.GLOBAL_COUNTERS.inc("spfft_cluster_routed_total",
                                     host=lane.host, kind=routed_kind)
            if self._remote:
                fut = self._fence_retry(
                    fut, lane, (signature, values, kind, scaling,
                                timeout, priority, ctx))
            return fut
        raise ClusterError(
            "no alive host lanes accepted the request (all transports "
            "down)")

    def _fence_retry(self, fut: Future, lane, request) -> Future:
        """Wrap a remote submit future with the epoch-fencing recovery
        contract: an agent-side :class:`StaleEpochError` (typed,
        transient) refetches the view and resubmits ONCE with the
        fresh stamp — transparent to the caller's future. Any other
        resolution passes through untouched."""
        outer: Future = Future()
        outer.set_running_or_notify_cancel()
        signature, values, kind, scaling, timeout, priority, ctx = \
            request

        def _copy(f: Future) -> None:
            exc = f.exception()
            if exc is None:
                outer.set_result(f.result())
            else:
                outer.set_exception(exc)

        def _first(f: Future) -> None:
            exc = f.exception()
            if not isinstance(exc, StaleEpochError):
                _copy(f)
                return
            try:
                self.view()
                retry = lane.rpc_submit(
                    signature, values, kind, scaling=scaling,
                    timeout=timeout, priority=priority, ctx=ctx,
                    epoch=self._stamp)
            except BaseException as rexc:
                outer.set_exception(rexc)
                return
            retry.add_done_callback(_copy)

        fut.add_done_callback(_first)
        return outer

    def _candidates(self) -> List[HostLane]:
        """Lanes in dispatch-preference order: the policy's pick first,
        then every other alive, non-draining lane as failover. Lanes on
        the resurrection ladder are NOT candidates — readmission, not
        the raw transport flag, controls candidacy."""
        self._maybe_probe()
        alive = [ln for ln in self._lanes
                 if ln.alive and not ln.draining
                 and not self._on_ladder(ln.host)]
        if len(alive) <= 1:
            return alive
        if self.policy == "rr":
            with self._rng_lock:
                start = self._rr_next % len(alive)
                self._rr_next += 1
            return alive[start:] + alive[:start]
        # power-of-two-choices: sample two distinct lanes, rank them by
        # live load, then append the rest as failover.
        with self._rng_lock:
            pair = self._rng.sample(range(len(alive)), 2)
        scored = []
        for i in pair:
            lane = alive[i]
            try:
                score = load_score(lane.rpc_signals())
            except HostLaneError:
                self._mark_dead(lane)
                continue
            scored.append((score, i, lane))
        scored.sort(key=lambda t: t[:2])
        picked = [lane for _, _, lane in scored]
        rest = [ln for ln in alive
                if ln.alive and ln not in picked]
        return picked + rest

    def _affinity_candidates(self, signature) -> List[HostLane]:
        """Lanes in dispatch order for a remote DISTRIBUTED request: a
        stable per-signature primary (crc32 of the signature's repr mod
        the alive-lane count) so concurrent same-signature requests
        co-locate and the host agent's coalescing window can merge
        them; the remaining alive lanes follow as failover."""
        self._maybe_probe()
        alive = [ln for ln in self._lanes
                 if ln.alive and not ln.draining
                 and not self._on_ladder(ln.host)]
        if len(alive) <= 1:
            return alive
        start = zlib.crc32(repr(signature).encode()) % len(alive)
        return alive[start:] + alive[:start]

    def _mark_dead(self, lane: HostLane) -> None:
        """A transport failure takes the lane out of routing — but no
        longer forever. The lane enters the resurrection ladder: its
        eviction bumps the view epoch (both frontends over a shared
        coordinator observe it), and backoff-spaced health probes keep
        testing it until re-reconciliation readmits it warm."""
        if lane.transport.alive:
            lane.transport.alive = False
        _obs.GLOBAL_COUNTERS.inc("spfft_cluster_lane_deaths_total",
                                 host=lane.host)
        with self._dead_lock:
            fresh = lane.host not in self._dead
            if fresh:
                base = self._probe_backoff()
                with self._rng_lock:
                    jitter = 1.0 + self._rng.random() * 0.25
                self._dead[lane.host] = [0,
                                         time.monotonic() + base * jitter]
        if fresh:
            _obs.record_event("lane.death", host=lane.host)
            self._membership.evict(lane.host)
            self._count_membership("evicted")
            if not self._remote:
                self._stamp = self._membership.epoch
            # a lane death is a flight-recorder auto trigger: the pod
            # just lost capacity, snapshot the black box while the
            # failure's trace tail is still in the retained ring
            _obs.maybe_auto_capture("lane_death", lane.host)

    def _probe_backoff(self) -> float:
        from ..control.config import global_config
        return float(global_config().lane_probe_backoff)

    def _on_ladder(self, host: str) -> bool:
        with self._dead_lock:
            return host in self._dead

    def _maybe_probe(self, now: Optional[float] = None) -> None:
        """Opportunistic resurrection: routing notices a dead lane
        whose backoff deadline has passed and SCHEDULES its probe on
        the background worker. The submit path never blocks on the
        health RPC or the readmission gate (strict prewarm +
        re-reconcile, which may compile plans) — a due probe costs a
        live request one set-membership check and a thread-pool
        enqueue."""
        if now is None:
            now = time.monotonic()
        with self._dead_lock:
            due = [h for h, (_, deadline) in self._dead.items()
                   if now >= deadline and h not in self._probing]
            self._probing.update(due)
        for host in due:
            try:
                self._probe_pool.submit(self._probe_bg, host)
            except RuntimeError:  # pool shut down mid-close
                with self._dead_lock:
                    self._probing.discard(host)

    def _probe_bg(self, host: str) -> None:
        """One scheduled background probe (the worker half of
        :meth:`_maybe_probe`)."""
        try:
            lane = next((ln for ln in self._lanes if ln.host == host),
                        None)
            if lane is None:  # left the pod while on the ladder
                with self._dead_lock:
                    self._dead.pop(host, None)
                return
            if not self._closed:
                self._probe(lane, time.monotonic())
        finally:
            with self._dead_lock:
                self._probing.discard(host)

    def probe_dead(self, force: bool = False) -> Dict[str, str]:
        """Ops/chaos entry point: walk the resurrection ladder NOW
        (synchronously — unlike routing's background scheduling).
        Returns per-host outcomes (``backoff`` when the next probe is
        not yet due and ``force`` is False, ``probing`` when a
        background probe already has the host in flight, else
        ``failed`` / ``blocked`` / ``readmitted``)."""
        now = time.monotonic()
        with self._dead_lock:
            entries = [(h, deadline)
                       for h, (_, deadline) in self._dead.items()]
        out: Dict[str, str] = {}
        for host, deadline in entries:
            if not force and now < deadline:
                out[host] = "backoff"
                continue
            with self._dead_lock:
                if host in self._probing:
                    out[host] = "probing"
                    continue
                self._probing.add(host)
            try:
                lane = next(
                    (ln for ln in self._lanes if ln.host == host),
                    None)
                if lane is None:
                    with self._dead_lock:
                        self._dead.pop(host, None)
                    continue
                out[host] = self._probe(lane, now)
            finally:
                with self._dead_lock:
                    self._probing.discard(host)
        return out

    def _probe(self, lane: HostLane, now: float) -> str:
        """One ladder step: health-probe the dead lane; on success run
        the readmission re-reconcile. A remote lane's death is only a
        cached belief about another process, so the probe re-tests the
        wire (the transport flag flips back on failure); a loopback
        lane's flag IS the simulated host state and is respected."""
        remote = hasattr(lane, "rpc_view")
        revived = False
        if remote and not lane.transport.alive:
            lane.transport.alive = True
            revived = True
        try:
            lane.rpc_health()
        except (HostLaneError, InjectedFault):
            if revived:
                lane.transport.alive = False
            _obs.GLOBAL_COUNTERS.inc("spfft_cluster_probes_total",
                                     host=lane.host, outcome="failed")
            _obs.record_event("lane.probe", host=lane.host,
                              outcome="failed")
            self._defer_probe(lane.host, now)
            return "failed"
        _obs.GLOBAL_COUNTERS.inc("spfft_cluster_probes_total",
                                 host=lane.host, outcome="ok")
        _obs.record_event("lane.probe", host=lane.host, outcome="ok")
        return self._readmit_lane(lane, now, revived)

    def _readmit_lane(self, lane: HostLane, now: float,
                      revived: bool) -> str:
        """The gate between 'answers health probes' and 'receives
        routes': re-reconcile the resurrected lane against an incumbent
        over the round-18 fingerprint-digest path. A host that came
        back serving a DIFFERENT plan set is blocked (typed, counted),
        not silently readmitted."""
        base = next(
            (ln for ln in self._lanes
             if ln.alive and not ln.draining and ln is not lane
             and not self._on_ladder(ln.host)), None)
        try:
            _faults.check_site("cluster.readmit")
            if base is not None:
                sigs = base.rpc_signatures()
                lane.rpc_prewarm(sigs, strict=True)
                self._reconcile_join(lane, base, sigs)
        except (ClusterReconciliationError, HostLaneError,
                PlanArtifactError, InjectedFault):
            if revived:
                lane.transport.alive = False
            _obs.GLOBAL_COUNTERS.inc("spfft_cluster_readmits_total",
                                     host=lane.host, outcome="blocked")
            self._defer_probe(lane.host, now)
            return "blocked"
        with self._dead_lock:
            self._dead.pop(lane.host, None)
        lane.transport.alive = True
        lane.draining = False
        self._membership.readmit(lane.host)
        self._count_membership("readmitted")
        if not self._remote:
            self._stamp = self._membership.epoch
        _obs.GLOBAL_COUNTERS.inc("spfft_cluster_readmits_total",
                                 host=lane.host, outcome="readmitted")
        _obs.record_event("lane.readmit", host=lane.host)
        return "readmitted"

    def _defer_probe(self, host: str, now: float) -> None:
        """Push the host's next probe out: exponential backoff from
        the ``lane_probe_backoff`` knob, capped at 64x, jittered from
        the frontend's seeded sampler (deterministic under chaos
        replay)."""
        with self._dead_lock:
            entry = self._dead.get(host)
            if entry is None:
                return
            entry[0] += 1
            delay = self._probe_backoff() * min(2 ** entry[0],
                                                _PROBE_BACKOFF_CAP)
            with self._rng_lock:
                delay *= 1.0 + self._rng.random() * 0.25
            entry[1] = now + delay

    def kill_host(self, host: str) -> None:
        """Chaos/ops entry point: take one lane out of the pod. Its
        executor is closed (resolving every queued future — completed
        or typed failure, never a hang), the lane stops receiving
        routes, and pod health degrades while survivors keep serving."""
        for lane in self._lanes:
            if lane.host == host:
                self._mark_dead(lane)
                if lane.executor is not None:
                    lane.executor.close()
                return
        raise InvalidParameterError(f"no lane named {host!r}")

    # -- elastic membership -------------------------------------------------
    @staticmethod
    def _count_membership(event: str) -> None:
        _obs.GLOBAL_COUNTERS.inc("spfft_cluster_membership_total",
                                 event=event)

    def join(self, lane) -> None:
        """Admit one lane into the LIVE pod. The joiner prewarms from
        an incumbent's signature set first (``rpc_prewarm`` resolves
        every single-device signature through the joiner's artifact
        tiers — memory, disk, remote blob — with zero builds; the
        distributed plans it must already have derived, they are never
        serialized), then an INCREMENTAL re-reconciliation checks the
        newcomer against one incumbent (the rest of the pod already
        agrees with it), and only then does the lane start receiving
        routes. A failed join leaves the membership exactly as it was
        and raises typed."""
        if self._closed:
            raise ClusterError("pod frontend is closed")
        if not isinstance(lane, HostLane):
            host, executor = lane
            lane = HostLane(host, executor)
        if any(ln.host == lane.host for ln in self._lanes):
            raise InvalidParameterError(
                f"host {lane.host!r} is already a pod member")
        self._count_membership("join_started")
        base = next(
            (ln for ln in self._lanes if ln.alive and not ln.draining),
            None)
        try:
            if base is None:
                raise ClusterError(
                    "no alive incumbent lane to join against")
            sigs = base.rpc_signatures()
            lane.rpc_prewarm(sigs, strict=True)
            self._count_membership("prewarmed")
            self._reconcile_join(lane, base, sigs)
            self._count_membership("reconciled")
        except Exception:
            self._count_membership("join_failed")
            raise
        self._lanes.append(lane)
        self._membership.ensure(lane.host)
        if not self._remote:
            self._stamp = self._membership.epoch
        self._count_membership("joined")

    def _reconcile_join(self, lane: HostLane, base: HostLane,
                        sigs) -> None:
        """The incremental half of :meth:`reconcile`: joiner vs one
        incumbent, signature-set containment plus per-plan descriptor
        agreement."""
        held = set(lane.rpc_signatures())
        missing = [s for s in sigs if s not in held]
        if missing:
            self._count_reconcile("mismatch")
            raise ClusterReconciliationError(
                f"joining host {lane.host!r} does not hold "
                f"{missing[:4]} after prewarm")
        for sig in sorted(sigs, key=repr):
            pair = [base.rpc_plan(sig), lane.rpc_plan(sig)]
            if any(p is None for p in pair):
                self._count_reconcile("mismatch")
                raise ClusterReconciliationError(
                    f"{sig} vanished during join reconciliation")
            self._reconcile_descriptors(sig, [base, lane], pair)
        self._count_reconcile("ok")

    def leave(self, host: str, drain: bool = True) -> dict:
        """Remove one lane from the live pod: it stops receiving new
        routes immediately (``draining``), optionally drains its queue
        to completion (every accepted future resolves), then leaves the
        membership."""
        lane = next((ln for ln in self._lanes if ln.host == host), None)
        if lane is None:
            raise InvalidParameterError(f"no lane named {host!r}")
        self._count_membership("leave_started")
        lane.draining = True
        drained = False
        if drain and lane.alive:
            try:
                lane.rpc_drain()
            except HostLaneError:
                self._mark_dead(lane)
            else:
                drained = True
                self._count_membership("drained")
        self._lanes.remove(lane)
        with self._dead_lock:
            self._dead.pop(host, None)
        self._membership.leave(host)
        if not self._remote:
            self._stamp = self._membership.epoch
        self._count_membership("left")
        return {"host": host, "drained": drained}

    # -- federated telemetry ------------------------------------------------
    def health(self) -> dict:
        """The pod ``/healthz`` snapshot: per-host states plus the
        aggregate. Worst alive-lane health wins; any dead lane floors
        the pod at ``degraded``; no alive lane at all is ``failed``."""
        hosts: Dict[str, dict] = {}
        worst = "healthy"
        dead = 0
        for lane in self._lanes:
            if not lane.alive:
                dead += 1
                hosts[lane.host] = {"state": "failed",
                                    "reason": "lane dead"}
                continue
            try:
                snap = lane.rpc_health()
            except HostLaneError:
                self._mark_dead(lane)
                dead += 1
                hosts[lane.host] = {"state": "failed",
                                    "reason": "health RPC failed"}
                continue
            hosts[lane.host] = snap
            state = snap.get("state", "healthy")
            if _STATE_RANK.get(state, 0) > _STATE_RANK[worst]:
                worst = state
        if dead:
            if dead == len(self._lanes):
                worst = "failed"
            elif _STATE_RANK[worst] < _STATE_RANK["degraded"]:
                worst = "degraded"
        counts = {s: 0 for s in _STATE_ORDER}
        for snap in hosts.values():
            counts[snap.get("state", "healthy")] = \
                counts.get(snap.get("state", "healthy"), 0) + 1
        for s in _STATE_ORDER:
            _obs.GLOBAL_COUNTERS.set("spfft_cluster_hosts",
                                     counts.get(s, 0), state=s)
            _obs.GLOBAL_COUNTERS.set("spfft_cluster_health",
                                     1.0 if s == worst else 0.0,
                                     state=s)
        return {"state": worst, "hosts": hosts,
                "alive": len(self._lanes) - dead,
                "lanes": len(self._lanes), "epoch": self._stamp}

    def metrics_text(self) -> str:
        """The pod ``/metrics``: this process's FULL exposition
        rendered exactly once (pod-level cluster series plus every
        process-global family — compile, faults, SLO, recorder,
        timing, trace — that an in-process lane's own exposition also
        carries), then every alive host's lane-level families with a
        ``host`` label merged in — parsed, not concatenated, so the
        result is one valid exposition document (one HELP/TYPE header
        per family) a scraper consumes directly.

        The merge is IDEMPOTENT: an in-process lane shares this
        process's counter registry, so only its per-executor
        ``spfft_serve_*`` / ``spfft_registry_*`` families federate
        (anything else it renders is a process-global already emitted
        above — re-exporting those once per lane double-counted every
        process-wide series under per-lane ``host`` labels). A remote
        lane's exposition is its own process's facts and merges whole;
        families that already carry a ``host`` label (membership, net)
        keep their own rather than being clobbered with the lane's."""
        self.health()  # refresh the aggregate gauges first
        b = _PromBuilder()
        seen = set()

        def _merge(name, value, labels):
            key = (name, tuple(sorted(labels.items())))
            if key in seen:
                return
            seen.add(key)
            mtype, help_ = METRIC_SPECS.get(name, ("gauge", name))
            b.add(name, mtype, help_, value, labels)

        for (name, labels), value in parse_prometheus_text(
                prometheus_text()).items():
            _merge(name, value, dict(labels))
        for lane in self._lanes:
            if not lane.alive:
                continue
            try:
                text = lane.rpc_metrics_text()
            except HostLaneError:
                self._mark_dead(lane)
                continue
            local = lane.executor is not None
            for (name, labels), value in \
                    parse_prometheus_text(text).items():
                if local and not name.startswith(_LANE_LEVEL_FAMILIES):
                    continue  # an in-process lane's process-globals
                merged = dict(labels)
                merged.setdefault("host", lane.host)
                _merge(name, value, merged)
        return b.text()

    def capture_incident(self, reason: str = "manual",
                         directory: Optional[str] = None
                         ) -> Optional[str]:
        """Pod-wide flight-recorder capture: gather every alive
        REMOTE lane's incident bundle over the wire (in-process lanes
        share this process's journal, contributed once under the
        coordinator's host name) and atomically write ONE
        host-labelled pod bundle with a single merged timeline.
        Returns the written path, or None on failure (counted,
        non-fatal). Registered as the recorder's incident capturer on
        construction, so auto triggers capture the whole pod."""
        from ..obs import recorder as _recorder
        local = self._membership.host
        bundles: Dict[str, dict] = {
            local: _recorder.build_incident_bundle(reason, host=local)}
        for lane in self._lanes:
            if lane.executor is not None or not lane.alive:
                continue  # in-process lanes share the local bundle
            try:
                bundles[lane.host] = lane.rpc_incident(reason)
            except (HostLaneError, ClusterError) as exc:
                bundles[lane.host] = {
                    "error": f"{type(exc).__name__}: {exc}"}
        pod = _recorder.merge_pod_bundle(reason, bundles)
        try:
            pod["health"] = self.health()
        except (ClusterError, HostLaneError):
            pass  # a mid-capture lane death must not lose the bundle
        try:
            path = _recorder.write_bundle(pod, directory=directory)
        except Exception as exc:
            _obs.GLOBAL_COUNTERS.inc(
                "spfft_recorder_incident_failures_total")
            _obs.record_event("incident.capture", reason=reason,
                              outcome=f"failed: {type(exc).__name__}")
            return None
        _obs.GLOBAL_COUNTERS.inc("spfft_recorder_incidents_total",
                                 trigger=reason.split(":", 1)[0])
        _obs.record_event("incident.capture", reason=reason,
                          outcome="written")
        return path

    # -- lifecycle ----------------------------------------------------------
    def close(self) -> None:
        """Close the SPMD lane and every alive lane's executor (remote
        lanes release their client pool; the agent process they front
        is not ours to stop)."""
        if self._closed:
            return
        self._closed = True
        from ..obs import recorder as _recorder
        if getattr(_recorder, "_capturer", None) \
                is self._incident_capturer:
            _recorder.set_incident_capturer(None)
            _recorder.set_health_provider(None)
        self._probe_pool.shutdown(wait=True, cancel_futures=True)
        self._spmd.close()
        for lane in self._lanes:
            if lane.executor is None:
                close = getattr(lane, "close", None)
                if close is not None:
                    close()
            elif lane.alive:
                lane.executor.close()

    def __enter__(self) -> "PodFrontend":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _loopback_allgather(rows: List[np.ndarray], index: int):
    """An emulated per-host allgather over precomputed per-host rows:
    host ``index``'s own contribution replaces its row (so a host lying
    about its digest is caught exactly as the real collective would)."""
    def allgather(x):
        out = [np.asarray(r) for r in rows]
        out[index] = np.asarray(x)
        return np.stack(out)
    return allgather


# ---------------------------------------------------------------------------
# Routing-policy simulation (the Round-18 benchmark scenario)
# ---------------------------------------------------------------------------

def simulate_routing(policy: str = "p2c", hosts: int = 2,
                     requests: int = 400, arrival_dt: float = 0.75,
                     heavy_cost: float = 8.0, light_cost: float = 1.0,
                     window: int = 32, seed: int = 0) -> Dict[str, object]:
    """Discrete-event skew scenario driving the REAL :func:`load_score`.

    Request ``i`` is heavy (``heavy_cost``) when ``i % hosts == 0``,
    light otherwise — precisely the arrival pattern that aliases every
    heavy request onto host 0 under round-robin (rotating start index
    ``i % hosts``), starving it while the other hosts idle. Each host
    is a single-server FIFO queue on a virtual clock; the signals a
    policy sees at dispatch time are what a live lane would report:
    ``queue_depth`` (requests assigned but not finished) and
    ``device_execute_p50`` (nearest-rank p50 of the last ``window``
    completed costs). Power-of-two-choices samples two hosts and takes
    the lower :func:`load_score`.

    Returns ``{"policy", "assigned", "completed", "ratio"}`` where
    ``completed`` counts per-host requests finished inside the arrival
    horizon and ``ratio`` is busiest/least-busy completed — the
    acceptance metric (rr ≥ 4, p2c ≤ 2 on the default scenario).
    """
    if policy not in ("p2c", "rr"):
        raise InvalidParameterError(
            f"policy must be 'p2c' or 'rr', got {policy!r}")
    rng = random.Random(seed)
    free_at = [0.0] * hosts           # server-busy-until, per host
    done: List[List[Tuple[float, float]]] = [[] for _ in range(hosts)]
    assigned = [0] * hosts

    def signals(h: int, now: float) -> Dict[str, float]:
        depth = sum(1 for t1, _ in done[h] if t1 > now)
        finished = sorted(t1 for t1, _ in done[h] if t1 <= now)
        costs = [c for t1, c in done[h] if t1 <= now]
        if costs:
            costs = costs[-window:]
            costs.sort()
            p50 = costs[(len(costs) - 1) // 2]
        else:
            p50 = 0.0
        del finished
        return {"queue_depth": depth, "device_execute_p50": p50}

    for i in range(requests):
        now = i * arrival_dt
        cost = heavy_cost if i % hosts == 0 else light_cost
        if policy == "rr" or hosts == 1:
            h = i % hosts
        else:
            a, b = rng.sample(range(hosts), 2)
            h = min((a, b),
                    key=lambda x: (load_score(signals(x, now)), x))
        start = max(now, free_at[h])
        free_at[h] = start + cost
        done[h].append((free_at[h], cost))
        assigned[h] += 1

    horizon = requests * arrival_dt
    completed = [sum(1 for t1, _ in d if t1 <= horizon) for d in done]
    ratio = max(completed) / max(1, min(completed))
    return {"policy": policy, "assigned": assigned,
            "completed": completed, "ratio": ratio}


# ---------------------------------------------------------------------------
# CLI: --smoke (2-host loopback pod) and --simulate (routing scenario)
# ---------------------------------------------------------------------------

def _run_simulate(seed: int = 0) -> Dict[str, object]:
    rr = simulate_routing("rr", seed=seed)
    p2c = simulate_routing("p2c", seed=seed)
    speedup = rr["ratio"] / max(p2c["ratio"], 1e-9)
    return {"rr_ratio": rr["ratio"], "p2c_ratio": p2c["ratio"],
            "rr_completed": rr["completed"],
            "p2c_completed": p2c["completed"],
            "imbalance_reduction_x": speedup}


def _run_smoke(seed: int = 0) -> int:
    """The ``make cluster-smoke`` body: a 2-host loopback pod serving a
    mixed single-device + distributed trace, checked for bit-exactness
    against direct plan calls, balanced routing, one trace id across
    the host boundary with valid parent/child nesting, a merged
    /metrics document that re-parses, and survivor serving after a
    lane death. Returns a process exit code."""
    from ..benchmark import cutoff_stick_triplets
    from ..parallel import make_distributed_plan, make_mesh
    from ..types import TransformType
    from ..utils.workloads import (even_plane_split,
                                   round_robin_stick_partition)
    from .registry import PlanRegistry, signature_for

    failures: List[str] = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    n = 10
    dims = (n, n, n)
    trip = cutoff_stick_triplets(n, n, n, 0.9, hermitian=False)
    rng = np.random.default_rng(seed)
    shards = 2

    _obs.enable()
    tracer = _obs.GLOBAL_TRACER
    tracer.reset()
    tracer.set_sample_rate(1.0)

    lanes = []
    local_plan = None
    local_sig = None
    dist_sig = None
    dplan0 = None
    for host in ("h0", "h1"):
        reg = PlanRegistry()
        sig, plan = reg.get_or_build(TransformType.C2C, *dims, trip,
                                     precision="double")
        parts = round_robin_stick_partition(trip, dims, shards)
        planes = even_plane_split(dims[2], shards)
        dplan = make_distributed_plan(TransformType.C2C, *dims, parts,
                                      planes, mesh=make_mesh(shards),
                                      precision="double")
        dsig = signature_for(TransformType.C2C, *dims, trip,
                             precision="double", device_count=shards)
        reg.put(dsig, dplan)
        lanes.append((host, ServeExecutor(reg)))
        if local_plan is None:
            local_plan, local_sig, dist_sig, dplan0 = \
                plan, sig, dsig, dplan

    pod = PodFrontend(lanes, policy="p2c", seed=seed)
    try:
        # -- mixed traffic: bit-exact vs direct plan calls -------------
        singles = []
        for _ in range(24):
            v = (rng.standard_normal(len(trip))
                 + 1j * rng.standard_normal(len(trip)))
            singles.append((v, pod.submit_backward(local_sig, v)))
        dvalues = [
            (rng.standard_normal(p.num_values)
             + 1j * rng.standard_normal(p.num_values))
            for p in dplan0.dist_plan.shard_plans]
        dfut = pod.submit(dist_sig, dvalues)
        for v, fut in singles:
            got = np.asarray(fut.result(timeout=120))
            want = np.asarray(local_plan.backward(v))
            check(np.array_equal(got, want),
                  "single-device result not bit-exact vs direct plan")
        dgot = np.asarray(dfut.result(timeout=120))
        dwant = np.asarray(dplan0.backward(dvalues))
        check(np.array_equal(dgot, dwant),
              "distributed result not bit-exact vs direct plan")

        # -- balanced routing ------------------------------------------
        comp = [lane.executor.metrics.snapshot()["completed"]
                for lane in pod._lanes]
        check(all(c >= 1 for c in comp),
              f"routing not balanced: per-host completed {comp}")

        # -- one trace id end-to-end, valid nesting --------------------
        check(tracer.open_count() == 0,
              f"{tracer.open_count()} unclosed spans: "
              f"{tracer.open_names()[:8]}")
        spans = [e for e in tracer.events()
                 if isinstance(e, _obs.Span)]
        roots = [s for s in spans if s.name == "cluster.request"]
        check(len(roots) == 25,
              f"expected 25 cluster.request roots, got {len(roots)}")
        by_id = {s.span_id: s for s in spans}
        crossed = 0
        for s in spans:
            if s.name in ("serve.request", "cluster.spmd_execute"):
                parent = by_id.get(s.parent_id)
                check(parent is not None and
                      parent.name == "cluster.request",
                      f"{s.name} span has no cluster.request parent")
                check(parent is None or
                      s.trace_id == parent.trace_id,
                      f"{s.name} trace id differs from its root")
                crossed += 1
        check(crossed >= 25,
              f"only {crossed} spans crossed the host boundary")

        # -- merged /metrics parses, host-labelled ---------------------
        parsed = _obs.parse_prometheus_text(pod.metrics_text())
        hosts_seen = {dict(labels).get("host")
                      for (name, labels) in parsed
                      if name == "spfft_serve_completed_total"}
        check({"h0", "h1"} <= hosts_seen,
              f"merged exposition missing hosts: {hosts_seen}")
        check(any(name == "spfft_cluster_routed_total"
                  for (name, _) in parsed),
              "merged exposition lacks pod-level cluster series")
        health = pod.health()
        check(health["state"] == "healthy",
              f"pod not healthy: {health['state']}")

        # -- lane death: degraded pod, survivors serve -----------------
        pod.kill_host("h1")
        check(pod.health()["state"] == "degraded",
              "pod not degraded after lane death")
        v = (rng.standard_normal(len(trip))
             + 1j * rng.standard_normal(len(trip)))
        got = np.asarray(pod.submit_backward(local_sig, v)
                         .result(timeout=120))
        check(np.array_equal(got, np.asarray(local_plan.backward(v))),
              "survivor host result not bit-exact after lane death")
        check(tracer.open_count() == 0,
              "unclosed spans after lane-death phase")
    finally:
        pod.close()
        _obs.disable()

    sim = _run_simulate(seed)
    check(sim["rr_ratio"] >= 4.0,
          f"rr skew scenario too mild: ratio {sim['rr_ratio']:.2f}")
    check(sim["p2c_ratio"] <= 2.0,
          f"p2c did not balance: ratio {sim['p2c_ratio']:.2f}")

    for msg in failures:
        print(f"cluster-smoke FAIL: {msg}")
    if failures:
        return 1
    print(f"cluster-smoke: 25 requests bit-exact across a 2-host pod "
          f"(routing completed={comp}), rr ratio "
          f"{sim['rr_ratio']:.2f} vs p2c {sim['p2c_ratio']:.2f}")
    print("CLUSTER SMOKE GREEN")
    return 0


def main(argv=None) -> int:
    import argparse
    import json as _json

    ap = argparse.ArgumentParser(
        prog="python -m spfft_tpu.serve.cluster",
        description="Pod frontend smoke + routing-policy simulation.")
    ap.add_argument("--smoke", action="store_true",
                    help="run the 2-host loopback pod smoke")
    ap.add_argument("--simulate", action="store_true",
                    help="print rr-vs-p2c routing ratios as JSON")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    if args.simulate:
        print(_json.dumps(_run_simulate(args.seed), indent=2))
        return 0
    if args.smoke:
        return _run_smoke(args.seed)
    ap.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())

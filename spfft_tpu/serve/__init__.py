"""spfft_tpu.serve — transform-as-a-service on top of compiled plans.

The serving layer the ROADMAP's "heavy traffic" north star needs, built
from three cooperating pieces:

* :mod:`~spfft_tpu.serve.registry` — ``PlanRegistry``, a byte-aware
  bounded LRU of ``TransformPlan``s keyed by a canonical
  ``PlanSignature`` (dims, sparse-index digest, transform type,
  precision, scaling, device count), with explicit ``warmup`` and
  hit/miss/eviction counters. Layered over the persistent XLA
  compilation cache, so a warm process skips both plan construction
  (~0.35 s at 256^3) and the compile.
* :mod:`~spfft_tpu.serve.executor` — ``ServeExecutor``, a concurrent
  batching executor: ``submit(signature, values)`` returns a future; a
  dispatcher thread buckets same-signature requests from per-signature
  pending shards and runs full buckets through the fused
  multi-transform path. ``priority="high"`` requests take a lane served
  before any normal work (EDF within each lane; a forming normal bucket
  closes its window early for urgent arrivals), and an adaptive
  batch-shape observer PINS exact batch shapes once a signature's
  traffic stabilises — stable traces dispatch with zero ladder pad
  rows. Bounded queue (``QueueFullError`` backpressure), per-request
  deadlines (``DeadlineExpiredError``), graceful serial degradation,
  reusable host staging buffers and double-buffered dispatch
  pipelining. Correctness contract: any interleaving of concurrent
  requests is bit-identical to running each request alone.
* :mod:`~spfft_tpu.serve.metrics` — ``ServeMetrics``: bounded
  per-priority-class latency reservoirs (p50/p95/p99), queue depth,
  split fused/serial batch histograms, pad-row and pinned-batch
  counters, orchestration overhead, and registry counters, integrated
  with :mod:`spfft_tpu.timing`'s exports.

* :mod:`~spfft_tpu.serve.faults` — ``FaultPlan``, the deterministic
  fault-injection seam behind the executor's failure handling:
  bucket-failure isolation (one poisoned request fails alone; healthy
  co-batched requests stay bit-exact), bounded retries with
  transient/permanent classification (``RetryExhaustedError``), device
  quarantine with probation/readmission (``NoHealthyDeviceError`` on an
  empty pool) and a crash-proof supervised dispatch loop
  (``ExecutorCrashedError``; health states via
  ``ServeMetrics.health()``). Quarantine counts only
  DEVICE-attributed failures (``attributes_device``) — a poisoned
  payload indicts the request, never the device it ran on. See
  docs/serving.md "Failure semantics".

End-to-end request observability lives in :mod:`spfft_tpu.obs`: when
tracing is enabled (``SPFFT_TPU_TRACE=1`` / ``obs.enable()``), every
sampled ``submit`` records spans for the full pipeline (submit →
queue-wait → bucket-formation → stage → dispatch → device-execute →
materialise → resolve) with retry/fallback/quarantine annotations,
exportable as Chrome trace JSON (Perfetto) and Prometheus text —
see docs/observability.md.

``python -m spfft_tpu.serve.bench`` replays a mixed-signature request
trace and reports p50/p95/p99 latency (per priority class with
``--high-fraction``) and throughput against a serial-loop baseline;
``--smoke`` is the deterministic tier-1 pinning check,
``--fault-smoke`` the deterministic failure-semantics check, and
``--fault-rate``/``--fault-script`` inject faults into a measured
replay.

Pod scale (round 18) lives in :mod:`~spfft_tpu.serve.cluster`:
``PodFrontend`` owns one ``ServeExecutor`` lane per host, reconciles
plan digests across hosts at construction, routes single-device
requests by power-of-two-choices over live load signals, hands
``DistributedTransformPlan`` requests to a pod-wide SPMD lane, and
federates telemetry (one trace id across the host boundary, one merged
``/metrics`` + worst-health-wins ``/healthz``). See docs/cluster.md;
``python -m spfft_tpu.serve.cluster --smoke`` is the tier-1 2-host
loopback check behind ``make cluster-smoke``.

Every tunable of this layer lives in the typed, hot-swappable
:class:`spfft_tpu.control.ServeConfig` (round 11) — a feedback
controller can retune a live executor from its own telemetry, an
offline auto-tuner emits the boot artifact, and an SLO watchdog
degrades ``health()`` when declared objectives burn. See
docs/control_plane.md.
"""

from ..errors import (ClusterError, ClusterReconciliationError,
                      DeadlineExpiredError, DistributedPlanUnsupportedError,
                      ExecutorCrashedError, HostLaneError,
                      NoHealthyDeviceError, PlanArtifactError,
                      QueueFullError, RetryExhaustedError, ServeError)
from .executor import PLAN_MANIFEST_ENV, ServeExecutor
from .faults import (FaultPlan, InjectedFault, attributes_device,
                     is_transient)
from .metrics import PRIORITY_CLASSES, ServeMetrics, percentile
from .registry import (PlanRegistry, PlanSignature, index_digest,
                       signature_for)


def __getattr__(name):
    # PEP 562 lazy re-export: `python -m spfft_tpu.serve.store` runs
    # store.py as __main__ AFTER this package imports — an eager
    # `from .store import ...` here would execute the module twice
    # (runpy's found-in-sys.modules RuntimeWarning). Everything else
    # reaches the store through these names on first touch.
    if name in ("PlanArtifactStore", "PLAN_STORE_ENV"):
        from . import store
        return getattr(store, name)
    if name in ("PodFrontend", "HostLane", "LoopbackTransport",
                "load_score", "simulate_routing"):
        # Same rationale: `python -m spfft_tpu.serve.cluster --smoke`
        # runs cluster.py as __main__.
        from . import cluster
        return getattr(cluster, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "PlanRegistry", "PlanSignature", "index_digest", "signature_for",
    "ServeExecutor", "ServeMetrics", "percentile", "PRIORITY_CLASSES",
    "PlanArtifactStore", "PLAN_STORE_ENV", "PLAN_MANIFEST_ENV",
    "FaultPlan", "InjectedFault", "is_transient", "attributes_device",
    "ServeError", "QueueFullError", "DeadlineExpiredError",
    "RetryExhaustedError", "NoHealthyDeviceError",
    "ExecutorCrashedError", "DistributedPlanUnsupportedError",
    "PlanArtifactError",
    "PodFrontend", "HostLane", "LoopbackTransport", "load_score",
    "simulate_routing",
    "ClusterError", "HostLaneError", "ClusterReconciliationError",
]

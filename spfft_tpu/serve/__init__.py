"""spfft_tpu.serve — transform-as-a-service on top of compiled plans.

The serving layer the ROADMAP's "heavy traffic" north star needs, built
from three cooperating pieces:

* :mod:`~spfft_tpu.serve.registry` — ``PlanRegistry``, a byte-aware
  bounded LRU of ``TransformPlan``s keyed by a canonical
  ``PlanSignature`` (dims, sparse-index digest, transform type,
  precision, scaling, device count), with explicit ``warmup`` and
  hit/miss/eviction counters. Layered over the persistent XLA
  compilation cache, so a warm process skips both plan construction
  (~0.35 s at 256^3) and the compile.
* :mod:`~spfft_tpu.serve.executor` — ``ServeExecutor``, a concurrent
  batching executor: ``submit(signature, values)`` returns a future; a
  dispatcher thread buckets same-signature requests inside a small time
  window and runs full buckets through the fused multi-transform path,
  with a bounded queue (``QueueFullError`` backpressure), per-request
  deadlines (``DeadlineExpiredError``) and graceful serial degradation.
  Correctness contract: any interleaving of concurrent requests is
  bit-identical to running each request alone.
* :mod:`~spfft_tpu.serve.metrics` — ``ServeMetrics``: per-request
  latency percentiles, queue depth, batch-size histogram and registry
  counters, integrated with :mod:`spfft_tpu.timing`'s exports.

``python -m spfft_tpu.serve.bench`` replays a mixed-signature request
trace and reports p50/p95/p99 latency and throughput against a
serial-loop baseline.
"""

from ..errors import DeadlineExpiredError, QueueFullError, ServeError
from .executor import ServeExecutor
from .metrics import ServeMetrics, percentile
from .registry import (PlanRegistry, PlanSignature, index_digest,
                       signature_for)

__all__ = [
    "PlanRegistry", "PlanSignature", "index_digest", "signature_for",
    "ServeExecutor", "ServeMetrics", "percentile",
    "ServeError", "QueueFullError", "DeadlineExpiredError",
]

"""Serving metrics: request latency, queue depth, batch sizes, registry
counters — one thread-safe sink shared by the executor and the bench CLI.

Integration with ``spfft_tpu.timing``: every completed request's latency
is also recorded into the global scope timer (``Timer.record``, the
cross-thread-safe path) under the ``serve.request`` label when timing is
enabled, so serving latencies appear in the same print/JSON exports the
reference-style benchmark already produces (rt_graph semantics,
src/timing/rt_graph.hpp). ``to_json`` embeds the full timing tree next
to the serving counters for one-file provenance.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional

from .. import timing


def percentile(samples: List[float], p: float) -> float:
    """Nearest-rank percentile (p in [0, 100]); 0.0 on no samples. The
    serving latency distribution is heavy-tailed (batching windows +
    compile hits), so nearest-rank — always a real sample — beats
    interpolation for honesty at the p99 tail."""
    if not samples:
        return 0.0
    s = sorted(samples)
    k = max(0, min(len(s) - 1, int(round(p / 100.0 * len(s) + 0.5)) - 1))
    return s[k]


class ServeMetrics:
    """Counters + distributions for one executor's lifetime.

    All mutation goes through the internal lock: the executor's
    dispatcher thread records completions while N submitter threads
    record enqueues/rejects concurrently.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        """Zero every counter/distribution (the bench CLI separates its
        warm phase from the measured replay this way). Quiesce the
        executor first — concurrent recording during a reset is not an
        error, but its samples land on whichever side of the reset the
        lock decides."""
        with self._lock:
            self._latencies: List[float] = []
            self._batch_hist: Dict[int, int] = {}
            self._fused_batches = 0
            self._serial_batches = 0
            self._completed = 0
            self._failed = 0
            self._rejected_queue_full = 0
            self._expired_deadline = 0
            self._queue_depth = 0
            self._max_queue_depth = 0

    # -- recording (executor-facing) ---------------------------------------
    def record_enqueue(self, depth: int) -> None:
        with self._lock:
            self._queue_depth = depth
            if depth > self._max_queue_depth:
                self._max_queue_depth = depth

    def record_dequeue(self, depth: int) -> None:
        with self._lock:
            self._queue_depth = depth

    def record_reject_queue_full(self) -> None:
        with self._lock:
            self._rejected_queue_full += 1

    def record_deadline_expired(self) -> None:
        with self._lock:
            self._expired_deadline += 1

    def record_batch(self, size: int, fused: bool) -> None:
        with self._lock:
            self._batch_hist[size] = self._batch_hist.get(size, 0) + 1
            if fused:
                self._fused_batches += 1
            else:
                self._serial_batches += 1

    def record_request_done(self, latency_s: float,
                            failed: bool = False) -> None:
        with self._lock:
            if failed:
                self._failed += 1
            else:
                self._completed += 1
                self._latencies.append(latency_s)
        if not failed and timing.enabled():
            timing.GlobalTimer.record("serve.request", latency_s)

    # -- reading -----------------------------------------------------------
    @property
    def fused_batches(self) -> int:
        with self._lock:
            return self._fused_batches

    @property
    def max_fused_batch_size(self) -> int:
        """Largest batch executed through the fused path so far (0 when
        none) — the fuzz tests' 'at least one fused batch >= 2'
        observable."""
        with self._lock:
            if not self._fused_batches:
                return 0
            return max((s for s, c in self._batch_hist.items()
                        if s >= 2 and c > 0), default=0)

    def latency_percentiles(self) -> Dict[str, float]:
        with self._lock:
            samples = list(self._latencies)
        return {"p50": percentile(samples, 50.0),
                "p95": percentile(samples, 95.0),
                "p99": percentile(samples, 99.0)}

    def snapshot(self, registry=None) -> Dict:
        """One JSON-ready dict of everything: counters, latency
        percentiles, the batch-size histogram, platform provenance and
        (when given) the registry's counter snapshot."""
        from ..utils.platform import platform_summary
        with self._lock:
            snap = {
                "completed": self._completed,
                "failed": self._failed,
                "rejected_queue_full": self._rejected_queue_full,
                "expired_deadline": self._expired_deadline,
                "queue_depth": self._queue_depth,
                "max_queue_depth": self._max_queue_depth,
                "fused_batches": self._fused_batches,
                "serial_batches": self._serial_batches,
                "batch_size_histogram": {str(k): v for k, v in
                                         sorted(self._batch_hist.items())},
                "latency_count": len(self._latencies),
            }
        snap["latency_seconds"] = self.latency_percentiles()
        snap["platform"] = platform_summary()
        if registry is not None:
            snap["registry"] = registry.stats()
        return snap

    def to_json(self, registry=None) -> str:
        """The snapshot plus the global timing tree (when any scopes
        were recorded) as one JSON document."""
        payload = self.snapshot(registry)
        timings = json.loads(timing.GlobalTimer.process().json())
        if timings.get("timings"):
            payload["timings"] = timings["timings"]
        return json.dumps(payload)

"""Serving metrics: request latency, queue depth, batch sizes, registry
counters — one thread-safe sink shared by the executor and the bench CLI.

Distributions are BOUNDED: latency samples live in per-priority-class
ring reservoirs (``latency_window`` most-recent samples per class), so a
long-lived server's percentiles stay a fixed-size, recent-window
statistic instead of an ever-growing list (the round-6 advisor finding:
one float per request forever). The total-count counters (``completed``,
``failed``, per-class completion counts) are exact over the lifetime.

Batch-size histograms are split per execution path: ``_fused_hist``
counts fused (vmapped planned-batch) buckets, ``_serial_hist`` counts
serially dispatched buckets — ``max_fused_batch_size`` reads the fused
histogram only, so a serial bucket of size >= 2 can no longer
masquerade as the largest fused batch. ``padded_rows`` accumulates the
pad rows the planned-batch ladder added (the adaptive pinning path's
success metric: ~0 on a stable-size trace) and ``pinned_batches`` counts
buckets dispatched at an exact pinned shape.

Integration with ``spfft_tpu.timing``: every completed request's latency
is also recorded into the global scope timer (``Timer.record``, the
cross-thread-safe path) under the ``serve.request`` label when timing is
enabled, so serving latencies appear in the same print/JSON exports the
reference-style benchmark already produces (rt_graph semantics,
src/timing/rt_graph.hpp). ``to_json`` embeds the full timing tree next
to the serving counters for one-file provenance.
"""

from __future__ import annotations

import collections
import json
import threading
from typing import Dict, List, Optional

from .. import timing

#: Priority classes the executor serves (submission order of lanes).
PRIORITY_CLASSES = ("high", "normal")

#: Default per-class latency reservoir size: large enough that p99 over
#: the window rests on ~40 real tail samples, small enough that a
#: million-request day holds ~64 KB of floats per class.
DEFAULT_LATENCY_WINDOW = 4096

#: Reservoir size for the control-plane signal distributions
#: (queue-wait per request, device-execute per bucket): the feedback
#: controller reads a RECENT-window percentile, so a smaller ring keeps
#: it responsive to regime changes.
DEFAULT_SIGNAL_WINDOW = 1024


def percentile(samples: List[float], p: float) -> float:
    """Nearest-rank percentile (p in [0, 100]); 0.0 on no samples. The
    serving latency distribution is heavy-tailed (batching windows +
    compile hits), so nearest-rank — always a real sample — beats
    interpolation for honesty at the p99 tail."""
    if not samples:
        return 0.0
    s = sorted(samples)
    k = max(0, min(len(s) - 1, int(round(p / 100.0 * len(s) + 0.5)) - 1))
    return s[k]


class ServeMetrics:
    """Counters + distributions for one executor's lifetime.

    All mutation goes through the internal lock: the executor's
    dispatcher thread records completions while N submitter threads
    record enqueues/rejects concurrently. The executor calls every
    ``record_*`` OUTSIDE its own queue lock, so metric contention never
    extends queue-lock hold times.
    """

    def __init__(self, latency_window: int = DEFAULT_LATENCY_WINDOW):
        self._lock = threading.Lock()
        self._window = max(1, int(latency_window))
        self.reset()

    def reset(self) -> None:
        """Zero every counter/distribution (the bench CLI separates its
        warm phase from the measured replay this way). Quiesce the
        executor first — concurrent recording during a reset is not an
        error, but its samples land on whichever side of the reset the
        lock decides."""
        with self._lock:
            #: guarded by _lock
            self._latencies: Dict[str, collections.deque] = {
                cls: collections.deque(maxlen=self._window)
                for cls in PRIORITY_CLASSES}
            #: guarded by _lock
            self._completed_by: Dict[str, int] = {
                cls: 0 for cls in PRIORITY_CLASSES}
            self._fused_hist: Dict[int, int] = {}   #: guarded by _lock
            self._serial_hist: Dict[int, int] = {}  #: guarded by _lock
            self._fused_batches = 0     #: guarded by _lock
            self._serial_batches = 0    #: guarded by _lock
            self._fused_rows = 0        #: guarded by _lock
            self._padded_rows = 0       #: guarded by _lock
            self._pinned_batches = 0    #: guarded by _lock
            # control-plane signal reservoirs (recent window):
            # queue-wait is enqueue -> dispatch per request (includes
            # the batching window a request sat out), device-execute is
            # dispatch -> materialised per bucket
            #: guarded by _lock
            self._queue_waits: collections.deque = collections.deque(
                maxlen=DEFAULT_SIGNAL_WINDOW)
            #: guarded by _lock
            self._device_exec: collections.deque = collections.deque(
                maxlen=DEFAULT_SIGNAL_WINDOW)
            self._stage_s = 0.0             #: guarded by _lock
            self._dispatch_s = 0.0          #: guarded by _lock
            # distributed-exchange overlap accounting (cumulative
            # seconds; the overlap_chunks controller rule diffs them)
            self._exchange_s = 0.0          #: guarded by _lock
            self._exchange_compute_s = 0.0  #: guarded by _lock
            self._completed = 0             #: guarded by _lock
            self._failed = 0                #: guarded by _lock
            self._rejected_queue_full = 0   #: guarded by _lock
            self._expired_deadline = 0      #: guarded by _lock
            self._purged_expired = 0        #: guarded by _lock
            self._queue_depth = 0           #: guarded by _lock
            self._max_queue_depth = 0       #: guarded by _lock
            # failure-handling counters (fault tolerance layer)
            self._retries = 0               #: guarded by _lock
            self._retries_exhausted = 0     #: guarded by _lock
            #: guarded by _lock
            self._retries_by: Dict[str, int] = {
                cls: 0 for cls in PRIORITY_CLASSES}
            #: guarded by _lock
            self._retries_exhausted_by: Dict[str, int] = {
                cls: 0 for cls in PRIORITY_CLASSES}
            self._bucket_fallbacks = 0      #: guarded by _lock
            self._quarantines = 0           #: guarded by _lock
            self._probations = 0            #: guarded by _lock
            self._readmissions = 0          #: guarded by _lock
            self._no_healthy_device = 0     #: guarded by _lock
            self._dispatcher_crashes = 0    #: guarded by _lock
            self._dispatcher_restarts = 0   #: guarded by _lock
            self._pin_prewarms = 0          #: guarded by _lock
            self._request_attributed_failures = 0  #: guarded by _lock
            self._slo_violations: tuple = ()       #: guarded by _lock
            self._health_state = "healthy"         #: guarded by _lock

    # -- recording (executor-facing) ---------------------------------------
    def record_enqueue(self, depth: int) -> None:
        with self._lock:
            self._queue_depth = depth
            if depth > self._max_queue_depth:
                self._max_queue_depth = depth

    def record_dequeue(self, depth: int) -> None:
        with self._lock:
            self._queue_depth = depth

    def record_reject_queue_full(self) -> None:
        with self._lock:
            self._rejected_queue_full += 1

    def record_deadline_expired(self, purged: bool = False) -> None:
        """One request whose deadline elapsed before dispatch;
        ``purged=True`` when ``submit``'s backpressure sweep reclaimed
        it from a full queue (counted in both tallies)."""
        with self._lock:
            self._expired_deadline += 1
            if purged:
                self._purged_expired += 1

    # -- failure handling (executor-facing) --------------------------------
    def record_retry(self, priority: str = "normal") -> None:
        """One recovery/retry execution of a single request, charged to
        its priority class (the executor's retry budget is
        per-priority)."""
        with self._lock:
            self._retries += 1
            self._retries_by[priority] += 1

    def record_retry_exhausted(self, priority: str = "normal") -> None:
        """A request's transient failure persisted through its whole
        per-priority retry budget."""
        with self._lock:
            self._retries_exhausted += 1
            self._retries_exhausted_by[priority] += 1

    def record_bucket_fallback(self) -> None:
        """A fused bucket raised and fell back to per-request serial
        re-execution (bucket-failure isolation)."""
        with self._lock:
            self._bucket_fallbacks += 1

    def record_quarantine(self) -> None:
        with self._lock:
            self._quarantines += 1

    def record_probation(self) -> None:
        """A quarantined device's backoff elapsed; a canary request is
        being routed to it."""
        with self._lock:
            self._probations += 1

    def record_readmission(self) -> None:
        """A probation canary succeeded; the device rejoined the pool."""
        with self._lock:
            self._readmissions += 1

    def record_no_healthy_device(self) -> None:
        with self._lock:
            self._no_healthy_device += 1

    def record_dispatcher_crash(self) -> None:
        with self._lock:
            self._dispatcher_crashes += 1

    def record_dispatcher_restart(self) -> None:
        with self._lock:
            self._dispatcher_restarts += 1

    def record_pin_prewarm(self) -> None:
        """A background exact-shape compile kicked off at streak
        pin_after - 1 (prewarm-on-pin)."""
        with self._lock:
            self._pin_prewarms += 1

    def record_request_attributed_failure(self) -> None:
        """A pooled execution failed with a REQUEST-attributed error
        (``faults.attributes_device`` said the payload, not the device,
        is the culprit) — the device's quarantine streak was NOT
        charged."""
        with self._lock:
            self._request_attributed_failures += 1

    def record_queue_waits(self, waits) -> None:
        """Enqueue->dispatch wait of each request in one dispatched
        bucket (seconds) — the controller's queue-pressure signal. One
        lock acquisition per bucket."""
        with self._lock:
            self._queue_waits.extend(waits)

    def record_device_execute(self, seconds: float) -> None:
        """Dispatch->materialised wall time of one bucket — the
        controller's device-cost signal (on accelerators this spans the
        async in-flight window; on CPU dispatch itself computes, so it
        is close to the dispatch overhead)."""
        with self._lock:
            self._device_exec.append(seconds)

    def record_slo(self, violations) -> None:
        """The SLO watchdog's verdict: the currently-burning objective
        names (empty = within budget). A non-empty set degrades the
        reported health of an otherwise-healthy executor; it never
        masks a worse lifecycle state."""
        with self._lock:
            self._slo_violations = tuple(violations)

    def record_health(self, state: str) -> None:
        """The executor pushes its lifecycle state here on transitions:
        ``healthy`` / ``degraded`` / ``draining`` / ``failed``."""
        with self._lock:
            prev = self._health_state
            self._health_state = state
        if state != prev:
            from .. import obs
            obs.record_event("health.transition", state=state, prev=prev)
            if state in ("degraded", "failed"):
                # a downward lifecycle transition is a flight-recorder
                # auto trigger: capture the black box at the moment the
                # executor's own health report worsens
                obs.maybe_auto_capture("health_" + state, state)

    def record_batch(self, size: int, fused: bool,
                     padded_rows: int = 0, pinned: bool = False,
                     stage_s: float = 0.0,
                     dispatch_s: float = 0.0) -> None:
        """One dispatched bucket: ``size`` live rows through the fused or
        serial path, ``padded_rows`` ladder pad rows it carried (fused
        path only), ``pinned`` when it ran at an exact pinned shape,
        plus its host-side orchestration cost — ``stage_s`` coercing/
        stacking payloads into the staging buffer, ``dispatch_s`` in the
        executable dispatch call (asynchronous on accelerators; on the
        CPU backend dispatch includes the compute itself). One lock
        acquisition per bucket — this is hot-path accounting."""
        with self._lock:
            hist = self._fused_hist if fused else self._serial_hist
            hist[size] = hist.get(size, 0) + 1
            if fused:
                self._fused_batches += 1
                self._fused_rows += int(size)
                self._padded_rows += int(padded_rows)
                if pinned:
                    self._pinned_batches += 1
            else:
                self._serial_batches += 1
            self._stage_s += stage_s
            self._dispatch_s += dispatch_s

    def record_exchange_overlap(self, exchange_s: float,
                                compute_s: float) -> None:
        """Cumulative exchange-vs-compute seconds for one distributed
        dispatch (from the overlap pipeline's recorded spans) — the
        signal pair the ``overlap_chunks`` controller rule diffs."""
        with self._lock:
            self._exchange_s += exchange_s
            self._exchange_compute_s += compute_s

    def record_request_done(self, latency_s: float, failed: bool = False,
                            priority: str = "normal") -> None:
        with self._lock:
            if failed:
                self._failed += 1
            else:
                self._completed += 1
                self._completed_by[priority] += 1
                self._latencies[priority].append(latency_s)
        if not failed and timing.enabled():
            timing.GlobalTimer.record("serve.request", latency_s)

    # -- reading -----------------------------------------------------------
    @property
    def fused_batches(self) -> int:
        with self._lock:
            return self._fused_batches

    @property
    def padded_rows(self) -> int:
        """Total ladder pad rows dispatched so far — ~0 once adaptive
        pinning has locked onto a stable batch size."""
        with self._lock:
            return self._padded_rows

    @property
    def pinned_batches(self) -> int:
        """Buckets dispatched at an exact pinned batch shape."""
        with self._lock:
            return self._pinned_batches

    @property
    def max_fused_batch_size(self) -> int:
        """Largest batch executed through the FUSED path so far (0 when
        none) — reads the fused histogram only, so serial buckets cannot
        inflate it."""
        with self._lock:
            return max(self._fused_hist, default=0)

    # lock: holds(_lock)
    def _health_locked(self) -> Dict:
        """Caller holds the lock — shared by :meth:`health` and the
        single-lock :meth:`snapshot`. The reported state is the
        executor's lifecycle state, degraded by an active SLO burn when
        (and only when) the lifecycle itself is healthy."""
        state = self._health_state
        if state == "healthy" and self._slo_violations:
            state = "degraded"
        return {
            "state": state,
            "lifecycle_state": self._health_state,
            "slo_violations": list(self._slo_violations),
            "request_attributed_failures":
                self._request_attributed_failures,
            "retries": self._retries,
            "retries_exhausted": self._retries_exhausted,
            "retries_by_class": dict(self._retries_by),
            "retries_exhausted_by_class": dict(
                self._retries_exhausted_by),
            "bucket_fallbacks": self._bucket_fallbacks,
            "quarantines": self._quarantines,
            "probations": self._probations,
            "readmissions": self._readmissions,
            "no_healthy_device": self._no_healthy_device,
            "dispatcher_crashes": self._dispatcher_crashes,
            "dispatcher_restarts": self._dispatcher_restarts,
            "pin_prewarms": self._pin_prewarms,
            "purged_expired": self._purged_expired,
        }

    def health(self) -> Dict:
        """One JSON-ready snapshot of the executor's failure-handling
        state: lifecycle state plus every fault-tolerance counter —
        retries, bucket fallbacks, quarantine lifecycle, dispatcher
        crash/restart tallies. This is the operator's first look when a
        service degrades: a climbing ``retries`` with zero
        ``retries_exhausted`` is riding out transients; climbing
        ``quarantines`` names a sick device; ``state == "failed"`` means
        the supervisor gave up and every pending future was failed."""
        with self._lock:
            return self._health_locked()

    def latency_percentiles(
            self, priority: Optional[str] = None) -> Dict[str, float]:
        """p50/p95/p99 over the bounded reservoir — one class when
        ``priority`` is given, all classes merged otherwise."""
        with self._lock:
            if priority is None:
                samples = [s for d in self._latencies.values() for s in d]
            else:
                samples = list(self._latencies[priority])
        return {"p50": percentile(samples, 50.0),
                "p95": percentile(samples, 95.0),
                "p99": percentile(samples, 99.0)}

    def signals(self) -> Dict:
        """The control plane's view: one consistent, JSON-ready dict of
        every signal the feedback controller and SLO watchdog consume —
        recent-window queue-wait / device-execute percentiles,
        cumulative batch/pad/overhead counters and the fused histogram
        (cumulative: the controller diffs successive snapshots itself,
        which keeps this read side stateless). One lock acquisition."""
        with self._lock:
            qw = list(self._queue_waits)
            dx = list(self._device_exec)
            lat = [s for d in self._latencies.values() for s in d]
            out = {
                "completed": self._completed,
                "failed": self._failed,
                "queue_depth": self._queue_depth,
                "max_queue_depth": self._max_queue_depth,
                "rejected_queue_full": self._rejected_queue_full,
                "padded_rows": self._padded_rows,
                "pinned_batches": self._pinned_batches,
                "fused_batches": self._fused_batches,
                "serial_batches": self._serial_batches,
                "fused_rows": self._fused_rows,
                "fused_hist": dict(self._fused_hist),
                "stage_s": self._stage_s,
                "dispatch_s": self._dispatch_s,
                "exchange_s": self._exchange_s,
                "exchange_compute_s": self._exchange_compute_s,
                "quarantines": self._quarantines,
            }
        out["queue_wait_p50"] = percentile(qw, 50.0)
        out["queue_wait_p95"] = percentile(qw, 95.0)
        out["device_execute_p50"] = percentile(dx, 50.0)
        out["device_execute_p95"] = percentile(dx, 95.0)
        out["latency_p99"] = percentile(lat, 99.0)
        return out

    def snapshot(self, registry=None) -> Dict:
        """One JSON-ready dict of everything: counters, latency
        percentiles (merged and per priority class), both batch-size
        histograms, pad-row/pinning counters, orchestration overhead,
        health, platform provenance and (when given) the registry's
        counter snapshot.

        CONSISTENCY contract (the obs-round satellite): every counter,
        the health block and the latency reservoirs are read under ONE
        lock acquisition, so an exporter scraping mid-traffic sees a
        mutually consistent point-in-time view (e.g. ``completed``
        equals the sum of ``completed_by_class``; a retry counted in
        ``health`` has its failure counted too). Platform and registry
        sections read other locks and may trail by a beat."""
        from ..utils.platform import platform_summary
        with self._lock:
            merged: Dict[int, int] = {}
            for hist in (self._fused_hist, self._serial_hist):
                for k, v in hist.items():
                    merged[k] = merged.get(k, 0) + v
            buckets = self._fused_batches + self._serial_batches
            lat = {cls: list(d) for cls, d in self._latencies.items()}
            qw = list(self._queue_waits)
            dx = list(self._device_exec)
            snap = {
                "completed": self._completed,
                "completed_by_class": dict(self._completed_by),
                "failed": self._failed,
                "rejected_queue_full": self._rejected_queue_full,
                "expired_deadline": self._expired_deadline,
                "queue_depth": self._queue_depth,
                "max_queue_depth": self._max_queue_depth,
                "fused_batches": self._fused_batches,
                "serial_batches": self._serial_batches,
                "fused_rows": self._fused_rows,
                "padded_rows": self._padded_rows,
                "pinned_batches": self._pinned_batches,
                "batch_size_histogram": {str(k): v for k, v in
                                         sorted(merged.items())},
                "fused_batch_histogram": {
                    str(k): v for k, v in sorted(self._fused_hist.items())},
                "serial_batch_histogram": {
                    str(k): v for k, v in sorted(self._serial_hist.items())},
                "latency_count": sum(len(d) for d in lat.values()),
                "latency_window": self._window,
                "overhead_seconds": {
                    "stage_total": self._stage_s,
                    "dispatch_total": self._dispatch_s,
                    "per_bucket": ((self._stage_s + self._dispatch_s)
                                   / buckets if buckets else 0.0),
                    "per_request": ((self._stage_s + self._dispatch_s)
                                    / self._completed
                                    if self._completed else 0.0),
                },
                "health": self._health_locked(),
            }
        snap["queue_wait_seconds"] = {
            "p50": percentile(qw, 50.0), "p95": percentile(qw, 95.0),
            "p99": percentile(qw, 99.0)}
        snap["device_execute_seconds"] = {
            "p50": percentile(dx, 50.0), "p95": percentile(dx, 95.0),
            "p99": percentile(dx, 99.0)}
        merged_lat = [s for d in lat.values() for s in d]
        snap["latency_seconds"] = {
            "p50": percentile(merged_lat, 50.0),
            "p95": percentile(merged_lat, 95.0),
            "p99": percentile(merged_lat, 99.0)}
        snap["latency_seconds_by_class"] = {
            cls: {"p50": percentile(lat[cls], 50.0),
                  "p95": percentile(lat[cls], 95.0),
                  "p99": percentile(lat[cls], 99.0)}
            for cls in PRIORITY_CLASSES}
        snap["platform"] = platform_summary()
        if registry is not None:
            snap["registry"] = registry.stats()
        return snap

    def to_json(self, registry=None, indent=None) -> str:
        """THE machine-readable serving summary — the one consistent
        snapshot plus the global timing tree (when any scopes were
        recorded) as one JSON document. ``serve.bench`` embeds
        ``json.loads(metrics.to_json(registry))`` instead of
        hand-building its own dict, and ``obs.prometheus_text`` renders
        the same snapshot — one source of truth for exporters."""
        payload = self.snapshot(registry)
        timings = json.loads(timing.GlobalTimer.process().json())
        if timings.get("timings"):
            payload["timings"] = timings["timings"]
        return json.dumps(payload, indent=indent)

"""Serving benchmark CLI: ``python -m spfft_tpu.serve.bench``.

Replays a mixed-signature request trace through the batching executor
and reports p50/p95/p99 request latency, throughput, batch-size
histogram and registry hit-rate against a serial-loop baseline: the same
trace executed by a caller WITHOUT the serving layer — it hand-builds a
plan per signature at first use (the cold plan cost the registry
amortises) and drives each request synchronously. The warm re-run of the
same loop is also measured and disclosed: on the CPU backend a warm
tight loop is the dispatch optimum, so the serving win there is plan
amortisation; fused batching and the device pool are TPU-regime levers
(see multi.FUSED_BATCH_MAX_GRID provenance).

Two extra modes exercise the adaptive dispatch path:

* ``--smoke`` — a fast, fully DETERMINISTIC trace (no threads, no
  batching windows: fixed-size waves drained synchronously) that
  asserts the adaptive pinning path activates and drives ladder pad
  rows to zero once pinned, with every result checked bit-exact against
  the serial oracle. Wired into tier-1 CI
  (tests/test_serve_bench_cli.py) — exit code 1 on any violated check.
* ``--high-fraction F`` — marks a deterministic F of the trace
  high-priority; the summary and JSON then carry per-class p50/p99 so
  the priority lane's latency separation under flood is measurable.
* ``--fault-rate R`` / ``--fault-script S`` — arm a deterministic
  ``faults.FaultPlan`` for the MEASURED replay (the warm phase runs
  clean), so graceful degradation under injected stage/dispatch/
  materialise/device faults is a recorded number (retries, bucket
  fallbacks, quarantine lifecycle, per-class p99 shift), not just an
  assertion.
* ``--fault-smoke`` — a fast, fully deterministic failure-semantics
  check (tier-1 CI, and the ``make ci-tpu`` lane next to the pinning
  smoke): a poisoned request in a fused bucket fails ALONE (co-batched
  requests bit-exact), a transiently-failing bucket recovers everyone,
  an always-failing device is quarantined while the pool keeps serving
  (then re-admitted via probation), and a scripted dispatch-loop crash
  resolves EVERY pending future with a typed error — zero hangs. Exit
  code 1 on any violation.
* ``--chaos SEED`` — the seeded chaos harness (``make chaos-smoke``):
  three deterministic acceptance phases (a fused-launch fault demotes
  exactly that plan direction and the next request succeeds; an
  injected ENOSPC flips the artifact store to the memory-only tier
  with ``health()`` degraded while serving continues; a wedged device
  execute trips the ``execute_timeout_ms`` watchdog and recovers) and
  then 16 seeded fault STORMS, all drawn from one RNG, across the
  package-wide fault seam (executor, plan build, registry, store).
  Invariants per storm: every future resolves (zero hangs), every
  failure is typed, healthy requests are bit-exact vs a clean serial
  oracle, zero unclosed obs spans, and the store never keeps a
  half-written artifact. Exit code 1 on any violation.

Observability (round 10): ``--trace-out FILE`` enables
``spfft_tpu.obs`` request tracing for the measured replay (or the
smoke waves) and exports the Chrome trace-event JSON — in the smoke
modes the trace is also VALIDATED (all eight request stages plus
compile and exchange events present, zero unclosed spans) and any
violation exits 1; ``--prom-out FILE`` writes the Prometheus text
exposition (round-tripped through the validating parser first);
``--profile-dir DIR`` captures a ``jax.profiler`` session around the
measured window. See docs/observability.md.

Control plane (round 11): ``--control`` arms the telemetry-driven
feedback controller (``spfft_tpu.control``) for the measured replay —
live retuning of batch window / pin policy / bucket cap / pipeline
depth from the metrics stream, every decision recorded; in ``--smoke``
it instead runs the deterministic scripted queue-buildup scenario and
asserts a recorded, bounds-clamped batch-window decision plus zero SLO
false positives (the round-11 acceptance observable). ``--slo`` declares
objectives for the SLO watchdog, ``--config`` loads a recommended-config
artifact (the ``python -m spfft_tpu.control tune`` output), and
``--metrics-port`` (or ``SPFFT_TPU_METRICS_PORT``) serves the HTTP
``/metrics`` / ``/healthz`` / ``/configz`` scrape endpoint for the
replay. See docs/control_plane.md.

The workload reuses the benchmark CLI's dense-within-cutoff stick
generator (``spfft_tpu.benchmark.cutoff_stick_triplets``, reference:
tests/programs/benchmark.cpp:176-205) at several sparsities, so the
trace mixes S distinct plan signatures over one grid size. CPU-runnable
at the default dims; on a TPU session the same flags exercise the
batched-grid Pallas path.

Prints a human summary plus exactly one JSON line (the bench.py
convention) with ``throughput_rps``, ``serial_throughput_rps``,
``speedup_vs_serial`` and the serving metrics snapshot.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def _parse_args(argv):
    p = argparse.ArgumentParser(
        prog="python -m spfft_tpu.serve.bench",
        description="spfft_tpu serving-layer benchmark (plan registry + "
                    "concurrent batching executor)")
    p.add_argument("--dim", type=int, default=24,
                   help="cubic grid size per signature (default 24, "
                        "CPU-friendly)")
    p.add_argument("--requests", type=int, default=96,
                   help="trace length (default 96)")
    p.add_argument("--signatures", type=int, default=3,
                   help="distinct plan signatures in the trace "
                        "(default 3); 1 = same-signature trace")
    p.add_argument("--threads", type=int, default=4,
                   help="submitter threads replaying the trace")
    p.add_argument("--window", type=float, default=None,
                   help="batching window seconds (default: the "
                        "executor's DEFAULT_BATCH_WINDOW)")
    p.add_argument("--max-batch", type=int, default=None,
                   help="bucket cap (default: the executor's "
                        "DEFAULT_MAX_BATCH)")
    p.add_argument("--max-queue", type=int, default=1024)
    p.add_argument("--no-batching", action="store_true",
                   help="degrade to serial dispatch (A/B the batcher)")
    p.add_argument("--pin-after", type=int, default=None,
                   help="consecutive same-size buckets before the exact "
                        "shape pins (default: DEFAULT_PIN_AFTER; 0 "
                        "disables pinning)")
    p.add_argument("--high-fraction", type=float, default=0.0,
                   help="fraction of trace requests submitted "
                        "priority='high' (default 0: all normal)")
    p.add_argument("--devices", type=int, default=0,
                   help="size of the executor's device pool (0 = all "
                        "visible devices; on a fresh CPU process this "
                        "also forces that many virtual CPU devices)")
    p.add_argument("--precision", choices=["single", "double"],
                   default="single")
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--cpu", action="store_true",
                   help="force the virtual CPU platform (like the test "
                        "conftest)")
    p.add_argument("--smoke", action="store_true",
                   help="fast deterministic pinning check (tier-1 CI): "
                        "fixed-size waves drained synchronously; "
                        "asserts pinned-path activation, zero pad rows "
                        "once pinned, and bit-exact results")
    p.add_argument("--fault-smoke", action="store_true",
                   help="fast deterministic failure-semantics check "
                        "(tier-1 CI + make ci-tpu): bucket isolation, "
                        "retry, quarantine/probation, crash-proof "
                        "dispatch — exit 1 on any violation")
    p.add_argument("--chaos", type=int, default=None, metavar="SEED",
                   help="run the seeded chaos harness: deterministic "
                        "degradation-ladder acceptance phases plus 16 "
                        "seeded multi-seam fault storms; exit 1 on any "
                        "violated invariant (make chaos-smoke)")
    p.add_argument("--fault-rate", type=float, default=0.0,
                   help="per-check probability of an injected transient "
                        "fault during the measured replay (seeded by "
                        "--seed; default 0 = no injection)")
    p.add_argument("--fault-script", default=None,
                   help="comma-separated scripted faults for the "
                        "measured replay, e.g. "
                        "'dispatch@3,device1@*:permanent' "
                        "(see spfft_tpu.serve.faults)")
    p.add_argument("--fault-scope", default=None,
                   help="restrict --fault-rate faults to one site "
                        "(stage|dispatch|materialise) or 'device:N'")
    p.add_argument("--trace-out", default=None, metavar="TRACE.json",
                   help="enable spfft_tpu.obs request tracing and write "
                        "the Chrome trace-event JSON here (open in "
                        "Perfetto / chrome://tracing); in the smoke "
                        "modes the trace is also validated (eight "
                        "request stages + compile/exchange events, "
                        "zero unclosed spans) — violations exit 1")
    p.add_argument("--prom-out", default=None, metavar="FILE.prom",
                   help="write obs.prometheus_text() (serving metrics + "
                        "registry + timing + obs counters) here; the "
                        "text is round-tripped through the exposition "
                        "parser first")
    p.add_argument("--profile-dir", default=None, metavar="DIR",
                   help="capture a jax.profiler trace of the measured "
                        "replay into DIR (the jax.named_scope phase "
                        "names become visible in the device profile)")
    p.add_argument("--control", action="store_true",
                   help="enable the telemetry-driven control plane: a "
                        "feedback controller retunes batch window / "
                        "pin policy / bucket cap / pipeline depth from "
                        "live metrics during the measured replay; in "
                        "--smoke it runs a deterministic scripted "
                        "queue-buildup scenario and asserts a recorded "
                        "bounds-clamped knob decision")
    p.add_argument("--control-interval", type=float, default=0.02,
                   help="controller step interval seconds for the live "
                        "replay loop (default 0.02)")
    p.add_argument("--slo", default=None, metavar="SPEC",
                   help="declare SLOs for the watchdog, e.g. "
                        "'p99_ms=50,error_rate=0.01,max_quarantines=0' "
                        "or '@objectives.json'; burn rates export as "
                        "spfft_slo_* gauges and a violation degrades "
                        "health()")
    p.add_argument("--config", default=None, metavar="CONFIG.json",
                   help="load a recommended-config artifact (the "
                        "'python -m spfft_tpu.control tune' output) as "
                        "the executor's boot config; explicit knob "
                        "flags still override it")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve GET /metrics (Prometheus text), "
                        "/healthz and /configz on 127.0.0.1:PORT for "
                        "the replay (0 = ephemeral port; default: the "
                        "SPFFT_TPU_METRICS_PORT env var, else off)")
    p.add_argument("-o", "--output", default=None, metavar="FILE.json")
    return p.parse_args(argv)


def _make_watchdog(args, metrics):
    """The --slo watchdog (None when undeclared). In the smoke modes a
    default generous healthy-trace spec is used when --control is on
    without --slo, so the no-false-positive property is always
    exercised."""
    from ..control import SLOSpec, SLOWatchdog
    if args.slo:
        return SLOWatchdog(metrics, SLOSpec.parse(args.slo))
    if args.control and (args.smoke or args.fault_smoke):
        return SLOWatchdog(metrics, SLOSpec(latency_p99_s=60.0,
                                            error_rate=0.5,
                                            max_quarantines=64))
    return None


def _metrics_port(args):
    from ..obs.http import port_from_env
    return args.metrics_port if args.metrics_port is not None \
        else port_from_env()


def _finish_obs(args, failures, metrics=None, registry=None,
                require_stages=False):
    """Shared --trace-out/--prom-out epilogue: export the trace (and
    structurally validate it in the smoke modes), check for unclosed
    spans, and write/validate the Prometheus text. Appends failure
    strings to ``failures``; returns an obs-summary dict for the JSON
    payload (None when obs was not requested)."""
    if not (args.trace_out or args.prom_out):
        return None
    from .. import obs
    summary = {}
    open_spans = obs.GLOBAL_TRACER.open_count()
    if open_spans:
        failures.append(
            f"{open_spans} unclosed spans after quiescence: "
            f"{obs.GLOBAL_TRACER.open_names()[:10]}")
    summary["open_spans"] = open_spans
    if args.trace_out:
        payload = obs.export_trace(args.trace_out)
        summary["trace_out"] = args.trace_out
        summary["trace_events"] = len(payload["traceEvents"])
        if require_stages:
            from ..obs.__main__ import (REQUEST_STAGES,
                                        validate_trace_payload)
            require = REQUEST_STAGES + ("compile.registry_build",)
            import jax
            if len(jax.devices()) >= 2:
                require = require + ("exchange.plan_build",)
            failures.extend(validate_trace_payload(
                payload, require_names=require))
        print(f"wrote {args.trace_out} "
              f"({summary['trace_events']} events)")
    if args.prom_out:
        text = obs.prometheus_text(metrics=metrics, registry=registry)
        try:
            series = obs.parse_prometheus_text(text)
            summary["prom_series"] = len(series)
        except ValueError as exc:
            failures.append(f"prometheus text failed to parse: {exc}")
        with open(args.prom_out, "w") as f:
            f.write(text)
        summary["prom_out"] = args.prom_out
        print(f"wrote {args.prom_out}")
    return summary


def _block(result) -> None:
    """Hard-materialise one result (host readback of one element)."""
    np.asarray(result).ravel()[:1]


def _run_control_scenario(args, ex, registry, sig, plan, make_vals,
                          wave, failures):
    """The deterministic closed-loop acceptance scenario (``--smoke
    --control``): a SCRIPTED queue buildup — several max_batch-sized
    waves staged before a single synchronous drain, so every request's
    recorded queue wait spans the buckets dispatched ahead of it —
    must make the feedback controller shrink the batching window:
    a recorded, bounds-clamped decision visible in the config history,
    the ``spfft_control_decisions_total`` counter and (when tracing) a
    ``control.retune`` annotation. Every buildup result is checked
    bit-exact against the serial oracle, one more wave is served AFTER
    the retune (mid-stream retune cannot perturb results), and the SLO
    watchdog must report zero violations on this healthy trace (the
    no-false-positive half of the acceptance criterion)."""
    from ..control import Controller, ServeConfig

    watchdog = _make_watchdog(args, ex.metrics)
    controller = Controller(ex.config, metrics=ex.metrics, executor=ex,
                            watchdog=watchdog)
    controller.step()  # baseline: deltas start at the post-wave state
    window_before = ex.config.batch_window
    if window_before <= 0.0:
        failures.append("control scenario needs a nonzero batch "
                        "window to retune")
    buildup = make_vals(6 * ex.config.max_batch)
    oracles = [np.asarray(plan.backward(v)) for v in buildup]
    futs = [ex.submit(sig, v) for v in buildup]
    ex._drain_once()
    decisions = controller.step()
    for i, (f, expect) in enumerate(zip(futs, oracles)):
        if not np.array_equal(np.asarray(f.result(timeout=60)), expect):
            failures.append(f"control buildup request {i} diverged "
                            f"from the serial oracle")
    window_after = ex.config.batch_window
    moved = [d for d in controller.decisions()
             if d.knob == "batch_window"]
    if not moved:
        failures.append(
            f"scripted queue buildup produced no batch_window "
            f"decision (window {window_before} -> {window_after}; "
            f"signals: {ex.metrics.signals()})")
    elif window_after >= window_before:
        failures.append(f"batch_window did not shrink under buildup: "
                        f"{window_before} -> {window_after}")
    lo, hi = ServeConfig.bounds("batch_window")
    if not lo <= window_after <= hi:
        failures.append(f"batch_window left its declared bounds: "
                        f"{window_after} not in [{lo}, {hi}]")
    from .. import obs as _obs_mod
    if _obs_mod.GLOBAL_COUNTERS.get(
            "spfft_control_decisions_total", knob="batch_window",
            source="controller") < 1:
        failures.append("spfft_control_decisions_total{knob="
                        "batch_window,source=controller} not recorded")
    # one more wave AFTER the retune: a mid-stream knob change must not
    # perturb results (the correctness contract, observed)
    post = make_vals(wave)
    futs = [ex.submit(sig, v) for v in post]
    ex._drain_once()
    for i, (v, f) in enumerate(zip(post, futs)):
        if not np.array_equal(np.asarray(f.result(timeout=60)),
                              np.asarray(plan.backward(v))):
            failures.append(f"post-retune request {i} diverged from "
                            f"the serial oracle")
    slo_summary = None
    if watchdog is not None:
        slo_summary = watchdog.evaluate()
        if slo_summary["violations"]:
            failures.append(f"SLO false positive on a healthy trace: "
                            f"{slo_summary['violations']}")
    import dataclasses
    control_summary = {
        "decisions": [dataclasses.asdict(d)
                      for d in controller.decisions()],
        "window_before": window_before,
        "window_after": window_after,
        "bounds": [lo, hi],
        "knobs": ex.config.snapshot(),
        "steps": controller.steps,
    }
    return control_summary, slo_summary


def _run_smoke(args) -> int:
    """Deterministic pinning smoke: one signature, ``WAVES`` waves of
    ``WAVE`` (deliberately NOT a power of two) requests, each wave
    staged then drained synchronously — bucket sizes are exact by
    construction, so the adaptive observer's behaviour is reproducible:
    the first ``pin_after`` waves pad ``WAVE`` up the pow2 ladder, every
    later wave dispatches at the pinned exact shape with zero pad rows.
    Every result is checked bit-exact against the serial oracle."""
    from ..benchmark import cutoff_stick_triplets
    from ..types import TransformType
    from .executor import DEFAULT_PIN_AFTER, ServeExecutor
    from .registry import PlanRegistry

    if args.trace_out or args.prom_out:
        from .. import obs
        obs.enable()
        obs.GLOBAL_TRACER.reset()

    n, WAVE, WAVES = 12, 5, 6
    pin_after = (args.pin_after if args.pin_after is not None
                 else DEFAULT_PIN_AFTER)
    triplets = cutoff_stick_triplets(n, n, n, 0.9, hermitian=False)
    registry = PlanRegistry()
    sig, plan = registry.get_or_build(
        TransformType.C2C, n, n, n, triplets, precision=args.precision)
    nv = plan.index_plan.num_values
    rng = np.random.default_rng(args.seed)
    cfg = None
    if args.config:
        from ..control import ServeConfig
        cfg = ServeConfig.load(args.config)
    # with --control the batching window stays at its (config) default
    # so the scripted buildup has a window for the controller to move;
    # _drain_once never waits windows, so the waves stay deterministic
    ex = ServeExecutor(registry, autostart=False,
                       batch_window=None if args.control else 0.0,
                       pin_after=pin_after, config=cfg)

    def make_vals(count):
        if args.precision == "single":
            return [rng.standard_normal((nv, 2)).astype(np.float32)
                    for _ in range(count)]
        return [rng.standard_normal(nv) + 1j * rng.standard_normal(nv)
                for _ in range(count)]

    failures = []
    pad_rows_per_wave = []
    for w in range(WAVES):
        vals = make_vals(WAVE)
        before = ex.metrics.padded_rows
        futures = [ex.submit(sig, v) for v in vals]
        ex._drain_once()
        pad_rows_per_wave.append(ex.metrics.padded_rows - before)
        for i, (v, f) in enumerate(zip(vals, futures)):
            if not np.array_equal(np.asarray(f.result()),
                                  np.asarray(plan.backward(v))):
                failures.append(f"wave {w} request {i} diverged from "
                                f"the serial oracle")
    control_summary = slo_summary = None
    if args.control:
        control_summary, slo_summary = _run_control_scenario(
            args, ex, registry, sig, plan, make_vals, WAVE, failures)
    snap = ex.metrics.snapshot(registry)
    ex.close()
    pinned = snap["pinned_batches"]
    if pin_after > 0:
        if pinned < 1:
            failures.append("pinned path never activated")
        if pad_rows_per_wave[-1] != 0:
            failures.append(
                f"stable-size trace still pads after pinning: "
                f"last wave added {pad_rows_per_wave[-1]} pad rows")
    if args.trace_out or args.prom_out:
        # exchange observability rides the smoke when a >= 2 device
        # mesh exists: a tiny chunked distributed plan records its
        # exact per-chunk wire accounting + HLO collective counts
        import jax
        if len(jax.devices()) >= 2:
            from .. import obs
            from ..parallel import make_distributed_plan, make_mesh
            from ..utils.workloads import (even_plane_split,
                                           round_robin_stick_partition)
            parts = round_robin_stick_partition(triplets, (n, n, n), 2)
            planes = even_plane_split(n, 2)
            dplan = make_distributed_plan(
                TransformType.C2C, n, n, n, parts, planes,
                mesh=make_mesh(2), precision=args.precision,
                overlap_chunks=2)
            dv = dplan.shard_values(
                [np.zeros(len(p),
                          np.complex64 if args.precision == "single"
                          else np.complex128) for p in parts])
            lowered = dplan._backward_jit.lower(dv,
                                                *dplan._device_tables)
            obs.record_hlo_counts("serve-smoke", lowered.as_text())
    obs_summary = _finish_obs(args, failures, metrics=ex.metrics,
                              registry=registry, require_stages=True)
    ok = not failures
    print(f"smoke: {WAVES} waves x {WAVE} requests, dim={n}^3, "
          f"pin_after={pin_after}")
    print(f"pad rows per wave: {pad_rows_per_wave} "
          f"(pinned_batches={pinned})")
    if control_summary is not None:
        print(f"control: {len(control_summary['decisions'])} "
              f"decisions, batch_window "
              f"{control_summary['window_before'] * 1e3:.2f} -> "
              f"{control_summary['window_after'] * 1e3:.2f} ms "
              f"(bounds {control_summary['bounds']})")
    if slo_summary is not None:
        print(f"slo: violations={slo_summary['violations'] or 'none'} "
              f"burn={ {k: round(v, 3) for k, v in slo_summary['burn'].items()} }")
    for msg in failures:
        print(f"FAIL: {msg}", file=sys.stderr)
    result = {
        "metric": f"serve.bench --smoke {n}^3 waves={WAVES}x{WAVE} "
                  f"(pinned_batches={pinned}, "
                  f"padded_rows={snap['padded_rows']})",
        "value": 1 if ok else 0,
        "unit": "ok",
        "smoke": True,
        "ok": ok,
        "pinned_batches": pinned,
        "padded_rows_total": snap["padded_rows"],
        "padded_rows_per_wave": pad_rows_per_wave,
        "failures": failures,
        "obs": obs_summary,
        "control": control_summary,
        "slo": slo_summary,
    }
    print(json.dumps(result))
    if args.output:
        with open(args.output, "w") as f:
            json.dump(result, f, indent=2)
        print(f"wrote {args.output}")
    return 0 if ok else 1


def _run_fault_smoke(args) -> int:
    """Deterministic failure-semantics smoke: every acceptance behavior
    of the fault-tolerance layer driven by scripted ``FaultPlan``s over
    synchronously drained waves (phases 1-4) and a live supervised
    dispatcher (phases 5-6) — no probabilistic faults, no timing races
    beyond one quarantine-backoff sleep. Exit code 1 on any violation:

    1. a fused bucket with one POISONED request fails only that request
       (co-batched requests bit-exact vs the serial oracle);
    2. a transiently-failing fused bucket recovers EVERY request via
       per-request serial retry;
    3. a device scripted to always fail is quarantined after
       ``quarantine_after`` consecutive failures and the pool keeps
       serving (every request still succeeds);
    4. a quarantined device whose fault cleared is re-admitted through
       a probation canary and the executor returns to healthy;
    5. a scripted dispatch-loop crash past the restart budget resolves
       every pending future with ``ExecutorCrashedError`` — zero hangs;
    6. the same crash WITHIN the restart budget restarts the loop and
       serves everything (degraded, not failed).
    """
    import jax

    from ..benchmark import cutoff_stick_triplets
    from ..errors import ExecutorCrashedError, ServeError
    from ..types import TransformType
    from .executor import ServeExecutor
    from .faults import FaultPlan
    from .registry import PlanRegistry

    if args.trace_out or args.prom_out:
        from .. import obs
        obs.enable()
        obs.GLOBAL_TRACER.reset()

    n = 12
    triplets = cutoff_stick_triplets(n, n, n, 0.9, hermitian=False)
    registry = PlanRegistry()
    sig, plan = registry.get_or_build(
        TransformType.C2C, n, n, n, triplets, precision=args.precision)
    nv = plan.index_plan.num_values
    rng = np.random.default_rng(args.seed)
    failures = []
    phases = {}

    def vals():
        if args.precision == "single":
            return rng.standard_normal((nv, 2)).astype(np.float32)
        return rng.standard_normal(nv) + 1j * rng.standard_normal(nv)

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    # -- phase 1: poisoned request fails ALONE ------------------------
    ex = ServeExecutor(registry, autostart=False, batch_window=0.0)
    good = [vals() for _ in range(3)]
    oracles = [np.asarray(plan.backward(v)) for v in good]
    futs = [ex.submit(sig, v) for v in good[:2]]
    poisoned = ex.submit(sig, np.zeros(3))  # wrong length: poisoned
    futs.append(ex.submit(sig, good[2]))
    ex._drain_once()
    for f, expect in zip(futs, oracles):
        check(np.array_equal(np.asarray(f.result(timeout=30)), expect),
              "phase1: healthy co-batched request diverged from oracle")
    try:
        poisoned.result(timeout=30)
        check(False, "phase1: poisoned request did not fail")
    except Exception:
        pass
    check(ex.metrics.health()["bucket_fallbacks"] >= 1,
          "phase1: fused bucket never fell back to serial recovery")
    ex.close()
    phases["1_poisoned_isolated"] = ex.metrics.health()

    # -- phase 2: transient bucket fault recovers everyone ------------
    ex = ServeExecutor(registry, autostart=False, batch_window=0.0,
                       fault_plan=FaultPlan(script="dispatch@1"))
    good = [vals() for _ in range(4)]
    oracles = [np.asarray(plan.backward(v)) for v in good]
    futs = [ex.submit(sig, v) for v in good]
    ex._drain_once()
    for f, expect in zip(futs, oracles):
        check(np.array_equal(np.asarray(f.result(timeout=30)), expect),
              "phase2: request not recovered bit-exact after transient "
              "bucket fault")
    h = ex.metrics.health()
    check(h["retries"] == 4 and h["retries_exhausted"] == 0,
          f"phase2: expected 4 clean retries, got {h}")
    ex.close()
    phases["2_transient_recovered"] = h

    # -- phases 3-4: quarantine + probation (need a 2+ device pool) ---
    pool = jax.devices()
    if len(pool) >= 2:
        ex = ServeExecutor(registry, autostart=False, devices=pool[:2],
                           quarantine_after=2, quarantine_backoff=30.0,
                           fault_plan=FaultPlan(script="device0@*"))
        for i in range(8):
            v = vals()
            expect = np.asarray(plan.backward(v))
            f = ex.submit(sig, v)
            ex._drain_once()
            check(np.array_equal(np.asarray(f.result(timeout=30)),
                                 expect),
                  f"phase3: request {i} failed under a sick device")
        h = ex.health()
        check(h["quarantines"] == 1,
              f"phase3: sick device not quarantined exactly once: {h}")
        check(h["devices"][0]["state"] == "quarantined",
              "phase3: device 0 not in quarantined state")
        check(h["state"] == "degraded",
              f"phase3: health should be degraded, got {h['state']}")
        ex.close()
        phases["3_quarantine"] = h

        ex = ServeExecutor(registry, autostart=False, devices=pool[:2],
                           quarantine_after=1, quarantine_backoff=0.05,
                           fault_plan=FaultPlan(script="device0@1"))
        v = vals()
        expect = np.asarray(plan.backward(v))
        f = ex.submit(sig, v)
        ex._drain_once()
        check(np.array_equal(np.asarray(f.result(timeout=30)), expect),
              "phase4: request not recovered around one-shot device "
              "fault")
        time.sleep(0.06)  # past the quarantine backoff: probation due
        v = vals()
        expect = np.asarray(plan.backward(v))
        f = ex.submit(sig, v)
        ex._drain_once()
        check(np.array_equal(np.asarray(f.result(timeout=30)), expect),
              "phase4: probation canary request failed")
        h = ex.health()
        check(h["probations"] == 1 and h["readmissions"] == 1,
              f"phase4: probation/readmission not observed: {h}")
        check(h["devices"][0]["state"] == "healthy"
              and h["state"] == "healthy",
              f"phase4: device not re-admitted to healthy: {h}")
        ex.close()
        phases["4_readmission"] = h
    else:
        phases["3_quarantine"] = phases["4_readmission"] = \
            f"skipped: single-device process ({len(pool)} visible)"

    # -- phase 5: loop crash past the budget fails every future -------
    ex = ServeExecutor(registry, autostart=False,
                       max_dispatch_restarts=0,
                       fault_plan=FaultPlan(script="loop@1:permanent"))
    futs = [ex.submit(sig, vals()) for _ in range(5)]
    ex.start()
    for i, f in enumerate(futs):
        try:
            f.result(timeout=30)
            check(False, f"phase5: future {i} resolved with a result "
                         f"after a dispatch-loop crash")
        except ExecutorCrashedError:
            pass
        except Exception as exc:
            check(False, f"phase5: future {i} failed with {type(exc)}, "
                         f"not ExecutorCrashedError")
    h = ex.metrics.health()
    check(h["state"] == "failed" and h["dispatcher_crashes"] == 1,
          f"phase5: supervisor state wrong after give-up: {h}")
    try:
        ex.submit(sig, vals())
        check(False, "phase5: submit accepted work on a failed executor")
    except ServeError:
        pass
    ex.close()
    phases["5_crash_fails_futures"] = h

    # -- phase 6: loop crash within the budget restarts and serves ----
    ex = ServeExecutor(registry, autostart=False,
                       max_dispatch_restarts=2,
                       fault_plan=FaultPlan(script="loop@1"))
    good = [vals() for _ in range(5)]
    oracles = [np.asarray(plan.backward(v)) for v in good]
    futs = [ex.submit(sig, v) for v in good]
    ex.start()
    for f, expect in zip(futs, oracles):
        check(np.array_equal(np.asarray(f.result(timeout=30)), expect),
              "phase6: request lost across a supervised restart")
    h = ex.metrics.health()
    check(h["dispatcher_restarts"] == 1 and h["state"] == "degraded",
          f"phase6: restart not recorded as degraded: {h}")
    ex.close()
    phases["6_crash_restart_recovers"] = h

    # the acceptance observable: EVERY span opened across all six
    # failure phases (poisoned buckets, injected faults, quarantines,
    # supervised crashes) closed — with error status on the failure
    # paths — before the executors quiesced
    obs_summary = _finish_obs(args, failures, metrics=ex.metrics,
                              registry=registry)
    ok = not failures
    print(f"fault smoke: dim={n}^3 precision={args.precision} "
          f"devices={len(pool)}")
    for name, h in phases.items():
        print(f"  {name}: {h}")
    for msg in failures:
        print(f"FAIL: {msg}", file=sys.stderr)
    result = {
        "metric": f"serve.bench --fault-smoke {n}^3 (6 phases: "
                  f"isolation/retry/quarantine/probation/crash/restart)",
        "value": 1 if ok else 0,
        "unit": "ok",
        "fault_smoke": True,
        "ok": ok,
        "failures": failures,
        "phases": {k: v for k, v in phases.items()},
        "obs": obs_summary,
    }
    print(json.dumps(result, default=str))
    if args.output:
        with open(args.output, "w") as f:
            json.dump(result, f, indent=2, default=str)
        print(f"wrote {args.output}")
    return 0 if ok else 1


def _run_chaos(args) -> int:
    """Seeded chaos harness (``--chaos SEED`` / ``make chaos-smoke``):
    the package-wide fault seam exercised end to end. Four
    deterministic acceptance phases prove each degradation ladder —

    A. a fused-kernel launch fault at execution time stickily demotes
       EXACTLY that plan direction to the unfused composition
       (recorded reason), the demoted retry is bit-exact, and the next
       request succeeds;
    B. an injected ENOSPC mid-spill flips the artifact store to the
       memory-only tier (``health()`` degraded, spills skipped,
       rejects counted) while serving continues, leaving no
       half-written artifact behind;
    C. a wedged bucket execute trips the ``execute_timeout_ms``
       watchdog into a typed transient failure and every request is
       recovered through the serial fallback;
    D. killing one host lane of a 2-host pod mid-trace degrades the
       pod, the killed lane's queue resolves typed (never hangs), and
       every post-kill request lands bit-exact on the survivor;
    D2. an armed ``cluster.spmd_window`` fault fails EVERY member of a
       coalesced SPMD round typed, and the next round (the one-shot
       script spent) is bit-exact —

    then 16 fault STORMS, every choice drawn from ONE seeded RNG: each
    storm arms a scripted multi-site :class:`~spfft_tpu.faults`
    ambient plan over a menu spanning four subsystems (executor
    stage/dispatch/materialise/loop, plan build, registry build, store
    load/spill/fsync/replace), drives a fresh registry + store +
    executor through a request wave, and asserts the invariants: every
    future resolves (zero hangs), every failure is a TYPED taxonomy
    error, healthy requests are bit-exact vs a clean serial oracle,
    zero unclosed obs spans after quiescence, and the store holds no
    torn ``.tmp-`` files and verifies clean. Phase G then arms the
    flight recorder over a live 2-host pod and proves the black box
    under fire: a lane death auto-captures a validating POD bundle
    holding the fault-site journal events and the typed failure's
    tail-retained trace, and an armed ``obs.capture`` fault fails the
    capture path contained (zero torn bundles) before healing. Exit
    code 1 on any violation."""
    import concurrent.futures as cf
    import os
    import shutil
    import tempfile

    from .. import faults, obs
    from ..benchmark import cutoff_stick_triplets
    from ..errors import GenericError
    from ..types import TransformType
    from .executor import ServeExecutor
    from .faults import FaultPlan
    from .registry import PlanRegistry
    from .store import PlanArtifactStore

    obs.enable()
    obs.GLOBAL_TRACER.reset()
    faults.disarm()
    seed = int(args.chaos)
    rng = np.random.default_rng(seed)
    failures: list = []
    phases = {}
    #: the typed-failure contract: every rejected/failed request raises
    #: a taxonomy error (GenericError covers Serve/TableBuild/Injected)
    #: or a request-shaped builtin (poisoned payloads)
    typed = (GenericError,) + faults.REQUEST_ERROR_TYPES
    fired_sites: dict = {}

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    def tally(plan_f):
        for s, c in plan_f.stats()["fired_by_site"].items():
            fired_sites[s] = fired_sites.get(s, 0) + c

    def spans_closed(where):
        n = obs.GLOBAL_TRACER.open_count()
        check(n == 0, f"{where}: {n} unclosed obs spans: "
                      f"{obs.GLOBAL_TRACER.open_names()[:10]}")

    def torn_files(root):
        return [f for _, _, fs in os.walk(root) for f in fs
                if f.startswith(".tmp-")]

    # -- phase A: fused-launch fault demotes exactly that direction ----
    env = {"SPFFT_TPU_FORCE_MATMUL_DFT": "1",
           "SPFFT_TPU_FUSED_INTERPRET": "1"}
    saved_env = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        from .. import make_local_plan
        trip = np.asarray([(x, y, z) for x in range(8) for y in range(6)
                           if (x + y) % 3 != 0 for z in range(0, 128, 2)],
                          np.int32)
        fp = make_local_plan(TransformType.C2C, 8, 6, 128, trip,
                             precision="single", use_pallas=True)
        nvf = fp.index_plan.num_values
        v = (rng.standard_normal(nvf)
             + 1j * rng.standard_normal(nvf)).astype(np.complex64)
        oracle = np.asarray(fp.backward(v))  # fused, disarmed
        check(not fp.fused_demotions(),
              "phaseA: plan started demoted on the CPU fused lane")
        kplan = FaultPlan(script="kernel.launch@1")
        faults.arm(kplan)
        out = np.asarray(fp.backward(v))  # demote + unfused retry
        faults.disarm()
        check(np.array_equal(out, oracle),
              "phaseA: demoted retry diverged from the fused result")
        dem = fp.fused_demotions()
        check(set(dem) == {"dec"},
              f"phaseA: expected exactly the backward direction "
              f"demoted, got {sorted(dem)}")
        check("runtime" in dem.get("dec", {}).get("reason", ""),
              f"phaseA: demotion reason not recorded: {dem}")
        out2 = np.asarray(fp.backward(v))  # next request: unfused path
        check(np.array_equal(out2, oracle),
              "phaseA: request after demotion failed or diverged")
        tally(kplan)
        phases["A_fused_demotion"] = dem
    finally:
        faults.disarm()
        for k, old in saved_env.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
    spans_closed("phaseA")

    # -- shared workload: one signature, one clean oracle plan ---------
    n = 10
    trip = cutoff_stick_triplets(n, n, n, 0.8, hermitian=False)
    oracle_reg = PlanRegistry(store=False)
    osig, oplan = oracle_reg.get_or_build(
        TransformType.C2C, n, n, n, trip, precision=args.precision)
    nv = oplan.index_plan.num_values

    def vals():
        if args.precision == "single":
            return rng.standard_normal((nv, 2)).astype(np.float32)
        return rng.standard_normal(nv) + 1j * rng.standard_normal(nv)

    # -- phase B: ENOSPC mid-spill -> memory-only tier, serving on -----
    tmp = tempfile.mkdtemp(prefix="spfft-chaos-store-")
    try:
        store = PlanArtifactStore(tmp)
        splan = FaultPlan(script="store.spill@1:enospc")
        faults.arm(splan)
        try:
            store.save_plan(osig, oplan, trip)
            check(False, "phaseB: injected ENOSPC did not surface")
        except OSError as exc:
            check(faults.is_persistent_disk_error(exc),
                  f"phaseB: ENOSPC surfaced untyped: {exc!r}")
        faults.disarm()
        check(store.degraded and store.health()["state"] == "degraded",
              f"phaseB: store not degraded after ENOSPC: "
              f"{store.health()}")
        # serving continues: spills are SKIPPED (counted), requests run
        key = store.save_plan(osig, oplan, trip)
        check(store.stats()["rejects"].get("degraded", 0) >= 1,
              f"phaseB: degraded spill not counted: {store.stats()}")
        check(not os.path.exists(store.artifact_path(key)),
              "phaseB: memory-only tier still wrote an artifact")
        with ServeExecutor(PlanRegistry(store=store), autostart=False,
                           batch_window=0.0) as ex:
            ex.registry.get_or_build(TransformType.C2C, n, n, n, trip,
                                     precision=args.precision)
            w = vals()
            f = ex.submit(osig, w)
            ex._drain_once()
            check(np.array_equal(np.asarray(f.result(timeout=60)),
                                 np.asarray(oplan.backward(w))),
                  "phaseB: request failed while the store is degraded")
        store.drain()
        check(not torn_files(tmp),
              "phaseB: torn .tmp- artifact left behind")
        tally(splan)
        phases["B_enospc_memory_only"] = store.health()
    finally:
        faults.disarm()
        shutil.rmtree(tmp, ignore_errors=True)
    spans_closed("phaseB")

    # -- phase C: execute watchdog turns a wedged execute transient ----
    wplan = FaultPlan(script="materialise@1:hang", hang_seconds=5.0)
    ex = ServeExecutor(PlanRegistry(store=False), autostart=False,
                       batch_window=0.0, fault_plan=wplan)
    ex.registry.get_or_build(TransformType.C2C, n, n, n, trip,
                             precision=args.precision)
    ex.config.set("execute_timeout_ms", 200, source="init",
                  reason="chaos watchdog phase")
    t0_wd = obs.GLOBAL_COUNTERS.get("spfft_execute_timeouts_total")
    good = [vals() for _ in range(3)]
    oracles = [np.asarray(oplan.backward(w)) for w in good]
    t_wedge = time.perf_counter()
    futs = [ex.submit(osig, w) for w in good]
    ex._drain_once()
    for i, (f, expect) in enumerate(zip(futs, oracles)):
        check(np.array_equal(np.asarray(f.result(timeout=60)), expect),
              f"phaseC: request {i} not recovered around the wedged "
              f"execute")
    elapsed = time.perf_counter() - t_wedge
    check(elapsed < 5.0,
          f"phaseC: recovery waited out the full hang "
          f"({elapsed:.1f} s) — watchdog never tripped")
    wd = obs.GLOBAL_COUNTERS.get("spfft_execute_timeouts_total") - t0_wd
    check(wd >= 1, "phaseC: spfft_execute_timeouts_total not bumped")
    h = ex.metrics.health()
    ex.close()
    check(h["bucket_fallbacks"] >= 1,
          f"phaseC: wedged bucket never fell back serial: {h}")
    tally(wplan)
    phases["C_execute_watchdog"] = {"timeouts": wd,
                                    "recovered_in_s": round(elapsed, 2)}
    spans_closed("phaseC")

    # -- phase D: pod lane death mid-trace -> degraded, survivors on --
    from .cluster import PodFrontend
    lanes = []
    for host in ("h0", "h1"):
        reg = PlanRegistry(store=False)
        reg.put(osig, oplan)
        lanes.append((host, ServeExecutor(reg)))
    pod = PodFrontend(lanes, seed=seed)
    try:
        good = [vals() for _ in range(8)]
        oracles = [np.asarray(oplan.backward(w)) for w in good]
        futs = [pod.submit_backward(osig, w) for w in good[:4]]
        pod.kill_host("h1")  # half the trace already in flight
        futs += [pod.submit_backward(osig, w) for w in good[4:]]
        served = failed = 0
        for i, (f, expect) in enumerate(zip(futs, oracles)):
            try:
                got = f.result(timeout=60)
            except cf.TimeoutError:
                check(False, f"phaseD: pod request {i} HUNG across "
                             f"the lane death")
            except typed:
                failed += 1  # killed lane's queue resolves typed
            except Exception as exc:
                check(False, f"phaseD: pod request {i} failed UNTYPED "
                             f"{type(exc).__name__}: {exc}")
            else:
                served += 1
                check(np.array_equal(np.asarray(got), expect),
                      f"phaseD: pod request {i} diverged from the "
                      f"serial oracle after the lane death")
        check(served >= 4,
              f"phaseD: survivor host served only {served}/8 — the "
              f"post-kill wave must all land on the live lane")
        h = pod.health()
        check(h["state"] == "degraded" and h["alive"] == 1,
              f"phaseD: pod health wrong after lane death: {h}")
        phases["D_pod_lane_death"] = {"served": served,
                                      "typed_failures": failed,
                                      "health": h["state"]}
    finally:
        pod.close()
        for _, ex_l in lanes:
            ex_l.close()
    spans_closed("phaseD")

    # -- phase D2: SPMD window fault fails the whole round typed ------
    # chaos-smoke runs on a 1-device mesh, so the storm aims a
    # duck-typed plan at the coalescer's window seam: one armed
    # ``cluster.spmd_window`` fault must fail EVERY coalesced member
    # typed, and the next round (fault spent) must be bit-exact.
    from ..control.config import global_config
    from ..types import Scaling
    from .cluster import SPMDCoalescer

    class _CoalescePlan:
        def coalesce_backward(self, values_list):
            return [np.asarray(v) * 2.0 for v in values_list]

    spmd_fp = FaultPlan(script="cluster.spmd_window@1")
    faults.arm(spmd_fp)
    spmd = SPMDCoalescer(max_workers=1)
    cfg_d2 = global_config()
    old_window = cfg_d2.spmd_batch_window
    cfg_d2.set("spmd_batch_window", 0.3, source="chaos",
               reason="phase D2 coalescing window")
    try:
        doomed = [spmd.submit(osig, _CoalescePlan(), vals(),
                              "backward", Scaling.NONE, None)
                  for _ in range(2)]
        spmd_failed = 0
        for i, f in enumerate(doomed):
            try:
                f.result(timeout=60)
                check(False, f"phaseD2: coalesced member {i} served "
                             f"through an armed window fault")
            except typed:
                spmd_failed += 1
            except Exception as exc:
                check(False, f"phaseD2: member {i} failed UNTYPED "
                             f"{type(exc).__name__}: {exc}")
        good_v = [vals() for _ in range(2)]
        healed = [spmd.submit(osig, _CoalescePlan(), v, "backward",
                              Scaling.NONE, None) for v in good_v]
        for i, (f, v) in enumerate(zip(healed, good_v)):
            check(np.array_equal(np.asarray(f.result(timeout=60)),
                                 np.asarray(v) * 2.0),
                  f"phaseD2: post-fault round member {i} diverged")
        sig_d2 = spmd.signals()
        check(sig_d2["spmd_coalesced"] >= 2,
              f"phaseD2: the window never coalesced: {sig_d2}")
    finally:
        faults.disarm()
        cfg_d2.set("spmd_batch_window", old_window, source="chaos",
                   reason="restore after phase D2")
        spmd.close()
    tally(spmd_fp)
    phases["D2_spmd_window_fault"] = {
        "typed_failures": spmd_failed,
        "coalesced": sig_d2["spmd_coalesced"],
        "launches": sig_d2["spmd_launches"]}
    spans_closed("phaseD2")

    # -- seeded storms -------------------------------------------------
    #: site menu: (site, subsystem, flow order, script kinds). Extras
    #: are only drawn from LATER flow stages than the primary, so the
    #: primary always fires even when it aborts the storm's flow.
    #: ``exchange.quantize`` leads the flow (the wire-ladder probe runs
    #: before everything else in a distributed plan build) and takes
    #: the dedicated dist-plan storm flow below instead of the
    #: registry/executor one.
    menu = (
        ("exchange.quantize", "exchange", 0, ("transient",)),
        ("store.load", "store", 1, ("transient", "enospc")),
        ("registry.build", "registry", 2, ("transient", "permanent")),
        ("plan.build", "plan", 3, ("transient", "permanent")),
        ("store.spill", "store", 4, ("transient", "enospc")),
        ("store.fsync", "store", 5, ("transient", "enospc")),
        ("store.replace", "store", 6, ("transient", "enospc")),
        ("stage", "executor", 7, ("transient", "permanent", "poison")),
        ("dispatch", "executor", 8, ("transient", "permanent")),
        ("materialise", "executor", 9, ("transient", "hang")),
        ("loop", "executor", 10, ("transient", "permanent")),
    )
    subsystem_of = {site: sub for site, sub, _, _ in menu}
    subsystem_of["cluster.spmd_window"] = "cluster"  # phase D2
    # shared fixture for the exchange.quantize storms: a 1-shard
    # distributed plan (chaos-smoke runs on one CPU device) whose wire
    # probe still exercises the int8 scale computation, plus a clean
    # full-rung oracle — at S=1 no collective runs, so the degraded
    # plan must stay BIT-exact, not merely within budget.
    from ..parallel.dist import DistributedTransformPlan, \
        build_distributed_plan
    wire_trip = cutoff_stick_triplets(8, 8, 8, 0.9, hermitian=False)
    wire_dp = build_distributed_plan(TransformType.C2C, 8, 8, 8,
                                     [wire_trip], [8])
    wire_oplan = DistributedTransformPlan(wire_dp, precision="single")
    nv_w = wire_dp.shard_plans[0].num_values
    wire_vals = [(rng.standard_normal(nv_w)
                  + 1j * rng.standard_normal(nv_w)).astype(np.complex64)]
    wire_oracle = np.asarray(wire_oplan.backward(wire_vals))
    storms = 16
    wave = 5
    storm_log = []
    for storm in range(storms):
        site, _, order, kinds = menu[storm % len(menu)]
        kind = kinds[int(rng.integers(len(kinds)))]
        # stage/dispatch are checked once per fused bucket and the wave
        # fits one bucket, so nth=2 would never fire there — only the
        # per-request/per-iteration sites (materialise, loop) can take
        # a deeper traversal
        nth = int(rng.integers(1, 3)) if order >= 9 else 1
        script = [f"{site}@{nth}:{kind}"]
        later = [m for m in menu if m[2] > order]
        if later and rng.random() < 0.5:
            extra = later[int(rng.integers(len(later)))]
            script.append(f"{extra[0]}@1:{extra[3][0]}")
        plan_f = FaultPlan(script=script, hang_seconds=0.2)
        if site == "exchange.quantize":
            # wire-ladder storm: the armed fault fires during the int8
            # probe's scale computation -> typed transient, the plan
            # falls back EXACTLY one rung (int8 -> bf16), records the
            # decline, and still serves bit-exact (S=1: no collective).
            obs.GLOBAL_TRACER.reset()
            outcome = {"script": script, "served": 0,
                       "typed_failures": 0, "wire_rung": None}
            try:
                faults.arm(plan_f)
                try:
                    wplan = DistributedTransformPlan(
                        wire_dp, precision="single",
                        wire_precision=3, wire_error_budget=1.0)
                except typed:
                    outcome["typed_failures"] += 1
                    check(False, f"storm {storm} {script}: quantize "
                                 f"fault ESCAPED the probe's decline "
                                 f"ladder")
                except Exception as exc:
                    check(False, f"storm {storm} {script}: UNTYPED "
                                 f"build failure "
                                 f"{type(exc).__name__}: {exc}")
                else:
                    outcome["wire_rung"] = wplan.wire_rung_name
                    check(wplan.wire_rung == 2,
                          f"storm {storm} {script}: faulted probe did "
                          f"not fall back one rung "
                          f"({wplan.wire_rung_name})")
                    check(("int8", "fault_injected")
                          in wplan.wire_declines,
                          f"storm {storm} {script}: decline reason not "
                          f"recorded: {wplan.wire_declines}")
                    got = np.asarray(wplan.backward(wire_vals))
                    check(np.array_equal(got, wire_oracle),
                          f"storm {storm} {script}: degraded-rung plan "
                          f"diverged from the oracle")
                    outcome["served"] += 1
                faults.disarm()
                spans_closed(f"storm {storm} {script}")
                tally(plan_f)
            finally:
                faults.disarm()
            storm_log.append(outcome)
            continue
        good = [vals() for _ in range(wave)]
        oracles = [np.asarray(oplan.backward(w)) for w in good]
        obs.GLOBAL_TRACER.reset()
        tmp = tempfile.mkdtemp(prefix="spfft-chaos-")
        outcome = {"script": script, "served": 0, "typed_failures": 0}
        try:
            faults.arm(plan_f)
            registry = PlanRegistry(store=PlanArtifactStore(tmp))
            try:
                sig, _ = registry.get_or_build(
                    TransformType.C2C, n, n, n, trip,
                    precision=args.precision)
            except typed:
                outcome["typed_failures"] += 1
                outcome["build"] = "typed failure"
            except Exception as exc:
                check(False, f"storm {storm} {script}: UNTYPED build "
                             f"failure {type(exc).__name__}: {exc}")
            else:
                ex = ServeExecutor(registry, autostart=False,
                                   batch_window=0.0,
                                   max_dispatch_restarts=2,
                                   fault_plan=plan_f)
                futs = [ex.submit(sig, w) for w in good]
                ex.start()
                for i, (f, expect) in enumerate(zip(futs, oracles)):
                    try:
                        got = f.result(timeout=120)
                    except cf.TimeoutError:
                        check(False, f"storm {storm} {script}: request "
                                     f"{i} HUNG")
                    except typed:
                        outcome["typed_failures"] += 1
                    except Exception as exc:
                        check(False,
                              f"storm {storm} {script}: request {i} "
                              f"failed UNTYPED "
                              f"{type(exc).__name__}: {exc}")
                    else:
                        outcome["served"] += 1
                        check(np.array_equal(np.asarray(got), expect),
                              f"storm {storm} {script}: request {i} "
                              f"diverged from the serial oracle")
                ex.close()
            if registry._disk is not None:
                registry._disk.drain()
            faults.disarm()
            check(not torn_files(tmp),
                  f"storm {storm} {script}: torn .tmp- artifact left")
            bad = [row for row in PlanArtifactStore(tmp).verify()
                   if not row.get("ok")]
            check(not bad,
                  f"storm {storm} {script}: store verify failed: {bad}")
            spans_closed(f"storm {storm} {script}")
            tally(plan_f)
        finally:
            faults.disarm()
            shutil.rmtree(tmp, ignore_errors=True)
        storm_log.append(outcome)

    # -- phase E: wire + blob storms over a live TCP agent -------------
    # The same seeded-storm discipline pointed at the pod's wire. One
    # in-process HostAgent serves every storm over real localhost
    # sockets; client and agent threads share the ambient plan, so the
    # ``net.*`` sites fire on BOTH ends — dropped/truncated frames,
    # refused accepts, mid-RPC socket death. Each storm also boots a
    # cold artifact store off a faulted remote blob tier. Invariants:
    # every wire failure is TYPED (``HostLaneError`` or a taxonomy
    # error off the error frame), zero hangs, a clean post-disarm
    # request is bit-exact, zero open spans — and blob faults stay
    # CONTAINED (the remote tier is best-effort: they become
    # ``spfft_store_remote_total{outcome="error"}`` counts, never a
    # request failure).
    from ..net.agent import HostAgent
    from ..net.blobstore import FileBlobStore
    from ..net.transport import TcpHostLane

    net_menu = (
        ("net.frame", "net", ("transient",)),
        ("net.send", "net", ("transient",)),
        ("net.recv", "net", ("transient", "hang")),
        ("net.accept", "net", ("transient",)),
        ("cluster.rpc", "cluster", ("transient",)),
        ("blob.get", "blob", ("transient",)),
        ("blob.put", "blob", ("transient",)),
    )
    subsystem_of.update({site: sub for site, sub, _ in net_menu})
    agent_reg = PlanRegistry(store=False)
    agent_reg.put(osig, oplan)
    agent_ex = ServeExecutor(agent_reg)
    agent = HostAgent("chaos-h0", agent_ex).start()
    blob_tmp = tempfile.mkdtemp(prefix="spfft-chaos-blob-")
    wire_storms = len(net_menu) + 1
    try:
        blob = FileBlobStore(blob_tmp)
        # seed the blob tier once, clean, so storm-time gets find a
        # real artifact behind the faulted fetch path
        seed_tmp = tempfile.mkdtemp(prefix="spfft-chaos-seed-")
        try:
            seed_store = PlanArtifactStore(seed_tmp, remote=blob)
            seed_store.save_plan(osig, oplan, trip)
            seed_store.drain()
        finally:
            shutil.rmtree(seed_tmp, ignore_errors=True)
        for storm in range(wire_storms):
            site, _, kinds = net_menu[storm % len(net_menu)]
            kind = kinds[int(rng.integers(len(kinds)))]
            nth = 1 if site.startswith("blob") \
                else int(rng.integers(1, 4))
            script = [f"{site}@{nth}:{kind}"]
            if rng.random() < 0.5:
                extra = net_menu[int(rng.integers(len(net_menu)))]
                if extra[0] != site:
                    script.append(f"{extra[0]}@1:{extra[2][0]}")
            plan_f = FaultPlan(script=script, hang_seconds=0.2)
            good = [vals() for _ in range(4)]
            oracles = [np.asarray(oplan.backward(w)) for w in good]
            obs.GLOBAL_TRACER.reset()
            outcome = {"script": script, "served": 0,
                       "typed_failures": 0, "wire": True}
            lane = TcpHostLane("chaos-h0", ("127.0.0.1", agent.port))
            boot_tmp = tempfile.mkdtemp(prefix="spfft-chaos-boot-")
            try:
                faults.arm(plan_f)
                futs = []
                for w in good:
                    try:
                        futs.append(lane.rpc_submit(osig, w,
                                                    ctx=None))
                    except typed:
                        outcome["typed_failures"] += 1
                        futs.append(None)
                    except Exception as exc:
                        check(False,
                              f"wire storm {storm} {script}: submit "
                              f"failed UNTYPED "
                              f"{type(exc).__name__}: {exc}")
                        futs.append(None)
                for i, (f, expect) in enumerate(zip(futs, oracles)):
                    if f is None:
                        continue
                    try:
                        got = f.result(timeout=60)
                    except cf.TimeoutError:
                        check(False, f"wire storm {storm} {script}: "
                                     f"request {i} HUNG")
                    except typed:
                        outcome["typed_failures"] += 1
                    except Exception as exc:
                        check(False,
                              f"wire storm {storm} {script}: request "
                              f"{i} failed UNTYPED "
                              f"{type(exc).__name__}: {exc}")
                    else:
                        outcome["served"] += 1
                        check(np.array_equal(np.asarray(got), expect),
                              f"wire storm {storm} {script}: request "
                              f"{i} diverged from the serial oracle")
                # cold boot off the faulted blob tier: contained, typed
                try:
                    boot_reg = PlanRegistry(
                        store=PlanArtifactStore(boot_tmp, remote=blob))
                    outcome["boot_warmed"] = \
                        boot_reg.prewarm_signatures([osig],
                                                    strict=False)
                    boot_reg.store.save_plan(osig, oplan, trip)
                    boot_reg.store.drain()
                except Exception as exc:
                    check(False,
                          f"wire storm {storm} {script}: blob-tier "
                          f"fault ESCAPED the best-effort seam as "
                          f"{type(exc).__name__}: {exc}")
                faults.disarm()
                # the wire heals: a clean request through the same
                # lane lands bit-exact
                w = vals()
                got = np.asarray(
                    lane.rpc_submit(osig, w, ctx=None)
                    .result(timeout=60))
                check(np.array_equal(got,
                                     np.asarray(oplan.backward(w))),
                      f"wire storm {storm} {script}: post-disarm "
                      f"request not bit-exact")
                spans_closed(f"wire storm {storm} {script}")
                tally(plan_f)
            finally:
                faults.disarm()
                lane.close()
                shutil.rmtree(boot_tmp, ignore_errors=True)
            storm_log.append(outcome)
    finally:
        faults.disarm()
        agent.close()
        agent_ex.close(drain=False)
        shutil.rmtree(blob_tmp, ignore_errors=True)
    phases["E_wire_blob_storms"] = {
        "storms": wire_storms,
        "served": sum(o["served"] for o in storm_log
                      if o.get("wire")),
        "typed_failures": sum(o["typed_failures"] for o in storm_log
                              if o.get("wire")),
    }
    spans_closed("phaseE")

    # -- phase F: partition storm — self-healing membership ------------
    # The round-21 liveness ladder under deterministic partitions.
    # F1: TWO frontends over the SAME loopback pod share one
    # ViewCoordinator — a lane death observed by frontend A evicts the
    # lane with an epoch bump, frontend B's stale stamp is fenced typed
    # (StaleEpochError, counted) and recovers by refetching, both
    # converge on the SAME epoch/view, survivors stay bit-exact, and
    # the resurrection ladder (probe -> blocked-under-fault ->
    # re-reconcile -> readmit) brings the lane back warm. F2: a
    # three-node lease-based membership on a fake clock — the
    # coordinator dies, its heartbeat targets re-elect the SAME
    # successor deterministically, an expired lease walks
    # suspected->probed->evicted, and a restarted node's next heartbeat
    # readmits it alive. The three round-21 sites (net.heartbeat,
    # cluster.view, cluster.readmit) each fire typed and contained.
    from ..errors import StaleEpochError
    from ..net.membership import (ALIVE, EVICTED, MembershipNode,
                                  ViewCoordinator)
    from .cluster import HostLane, PodFrontend

    subsystem_of.update({"net.heartbeat": "membership",
                         "cluster.view": "membership",
                         "cluster.readmit": "cluster"})

    # F1 — two-frontend convergence over a shared coordinator
    reg_f0 = PlanRegistry(store=False)
    reg_f0.put(osig, oplan)
    reg_f1 = PlanRegistry(store=False)
    reg_f1.put(osig, oplan)
    ex_f0 = ServeExecutor(reg_f0)
    ex_f1 = ServeExecutor(reg_f1)
    mm = ViewCoordinator("h0")
    fa = PodFrontend([HostLane("h0", ex_f0), HostLane("h1", ex_f1)],
                     membership=mm, seed=seed)
    fb = PodFrontend([HostLane("h0", ex_f0), HostLane("h1", ex_f1)],
                     membership=mm, seed=seed + 1)
    try:
        for front, tag in ((fa, "fa"), (fb, "fb")):
            w = vals()
            got = np.asarray(front.submit(osig, w).result(timeout=60))
            check(np.array_equal(got, np.asarray(oplan.backward(w))),
                  f"phaseF1: pre-storm request via {tag} diverged")
        epoch0 = fa.epoch
        check(fb.epoch == epoch0,
              f"phaseF1: frontends disagree pre-storm "
              f"({fa.epoch} vs {fb.epoch})")
        # frontend A observes h1's death: failover + eviction + bump.
        # _mark_dead is the detection event a failed RPC delivers
        # (kill_host would also close the executor we resurrect below).
        dead_lane = fa._lanes[1]
        fa._mark_dead(dead_lane)
        for _ in range(3):
            w = vals()
            got = np.asarray(fa.submit(osig, w).result(timeout=60))
            check(np.array_equal(got, np.asarray(oplan.backward(w))),
                  "phaseF1: survivor request diverged after kill")
        check(fa.epoch > epoch0,
              f"phaseF1: eviction did not bump the epoch "
              f"({epoch0} -> {fa.epoch})")
        # frontend B is now STALE: its next submit is fenced typed
        # (counted) and recovers by refetching the shared view
        stale0 = obs.GLOBAL_COUNTERS.get(
            "spfft_cluster_stale_epoch_total", node="frontend")
        w = vals()
        got = np.asarray(fb.submit(osig, w).result(timeout=60))
        check(np.array_equal(got, np.asarray(oplan.backward(w))),
              "phaseF1: stale frontend's request diverged")
        check(obs.GLOBAL_COUNTERS.get(
                  "spfft_cluster_stale_epoch_total",
                  node="frontend") > stale0,
              "phaseF1: stale frontend was not fenced typed")
        check(fb.epoch == fa.epoch,
              f"phaseF1: frontends did not converge after eviction "
              f"({fa.epoch} vs {fb.epoch})")
        va, vb = fa.view(), fb.view()
        check(va["epoch"] == vb["epoch"]
              and va["members"] == vb["members"],
              f"phaseF1: views diverge: {va} vs {vb}")
        check(va["members"]["h1"]["state"] == EVICTED,
              f"phaseF1: h1 not tombstoned evicted: {va}")
        # resurrection: readmission BLOCKED under an armed
        # cluster.readmit fault, then clean probe readmits warm
        dead_lane.transport.alive = True
        fplan = FaultPlan(script=["cluster.readmit@1"])
        faults.arm(fplan)
        out1 = fa.probe_dead(force=True)
        faults.disarm()
        tally(fplan)
        check(out1.get("h1") == "blocked",
              f"phaseF1: faulted readmit not blocked: {out1}")
        out2 = fa.probe_dead(force=True)
        check(out2.get("h1") == "readmitted",
              f"phaseF1: clean probe did not readmit: {out2}")
        check(fa.view()["members"]["h1"]["state"] == ALIVE,
              "phaseF1: readmitted lane not alive in the view")
        check(fb.view()["epoch"] == fa.epoch,
              "phaseF1: frontends did not converge after readmission")
        for front, tag in ((fa, "fa"), (fb, "fb")):
            w = vals()
            got = np.asarray(front.submit(osig, w).result(timeout=60))
            check(np.array_equal(got, np.asarray(oplan.backward(w))),
                  f"phaseF1: post-readmit request via {tag} diverged")
        phases["F1_two_frontend_convergence"] = {
            "epoch": fa.epoch, "members": fa.view()["members"]}
    finally:
        faults.disarm()
        fa.close()
        fb.close()
    spans_closed("phaseF1")

    # F2 — lease expiry, deterministic re-election, heartbeat readmit
    now_s = [0.0]
    nodes: dict = {}
    down: set = set()

    def mem_wire(addr, hdr):
        if addr in down:
            raise OSError(f"{addr} unreachable (partitioned)")
        return nodes[addr].on_heartbeat(str(hdr["host"]),
                                        hdr.get("address"))

    for h in ("m0", "m1", "m2"):
        peers = {p: p for p in ("m0", "m1", "m2") if p != h}
        nodes[h] = MembershipNode(h, address=h, peers=peers,
                                  clock=lambda: now_s[0], secret=None)
    check(nodes["m0"].is_coordinator
          and not nodes["m1"].is_coordinator,
          "phaseF2: lowest host id is not the initial coordinator")
    for h in ("m1", "m2"):
        check(nodes[h].tick(mem_wire) == "ok",
              f"phaseF2: initial heartbeat from {h} failed")
    # net.heartbeat fires typed and is CONTAINED in the tick
    fplan = FaultPlan(script=["net.heartbeat@1"])
    faults.arm(fplan)
    check(nodes["m1"].tick(mem_wire) == "failed",
          "phaseF2: faulted heartbeat not contained as 'failed'")
    faults.disarm()
    tally(fplan)
    check(nodes["m1"].tick(mem_wire) == "ok",
          "phaseF2: heartbeat did not recover post-disarm")
    # cluster.view fires typed on view serving
    fplan = FaultPlan(script=["cluster.view@1"])
    faults.arm(fplan)
    try:
        nodes["m0"].on_view()
        check(False, "phaseF2: armed cluster.view did not fire")
    except typed:
        pass
    faults.disarm()
    tally(fplan)
    for h in ("m1", "m2"):
        check(nodes[h].adopt(nodes["m0"].on_view()),
              f"phaseF2: {h} did not adopt the coordinator view")
    # kill the coordinator: its heartbeat targets re-elect the SAME
    # successor (lowest alive id) after COORD_FAIL_STREAK failures
    down.add("m0")
    outcomes = [nodes["m1"].tick(mem_wire) for _ in range(3)]
    check(outcomes[-1] == "promoted",
          f"phaseF2: m1 did not promote itself: {outcomes}")
    check(nodes["m1"].is_coordinator,
          "phaseF2: promoted node is not coordinator")
    m2_out = [nodes["m2"].tick(mem_wire) for _ in range(4)]
    check("re-elected" in m2_out and m2_out[-1] == "ok",
          f"phaseF2: m2 did not re-elect and re-target m1: {m2_out}")
    check(nodes["m2"].adopt(nodes["m1"].on_view()),
          "phaseF2: m2 did not adopt the new coordinator's view")
    check(nodes["m2"].epoch == nodes["m1"].epoch,
          f"phaseF2: epochs diverge after election "
          f"({nodes['m1'].epoch} vs {nodes['m2'].epoch})")
    # lease expiry ladder: m2 stops renewing, the clock runs past
    # EVICT_AFTER x TTL, the coordinator evicts it with a bump
    pre_evict = nodes["m1"].epoch
    now_s[0] += 10.0
    nodes["m1"].tick(mem_wire)  # coordinator tick runs expiry
    states = {h: r["state"]
              for h, r in nodes["m1"].on_view()["members"].items()}
    check(states.get("m2") == EVICTED,
          f"phaseF2: silent m2 not evicted by lease expiry: {states}")
    check(nodes["m1"].epoch > pre_evict,
          "phaseF2: lease eviction did not bump the epoch")
    # epoch fencing at the agent door: the pre-eviction stamp is
    # rejected typed, the current stamp passes
    try:
        nodes["m1"].check_epoch(pre_evict - 1)
        check(False, "phaseF2: stale epoch stamp not fenced")
    except StaleEpochError:
        pass
    nodes["m1"].check_epoch(nodes["m1"].epoch)
    # restart: the evicted node's next heartbeat readmits it alive
    check(nodes["m2"].tick(mem_wire) == "ok",
          "phaseF2: restarted node's heartbeat failed")
    states = {h: r["state"]
              for h, r in nodes["m1"].on_view()["members"].items()}
    check(states.get("m2") == ALIVE,
          f"phaseF2: restarted m2 not readmitted alive: {states}")
    phases["F2_lease_election"] = {
        "coordinator": nodes["m1"].coordinator()[0],
        "epoch": nodes["m1"].epoch, "states": states}
    spans_closed("phaseF2")

    # -- phase G: flight recorder — auto-captured incident bundles -----
    # The black box under fire. G1: the recorder armed over a live
    # 2-host loopback pod — a transient executor fault journals its
    # firing, a poisoned request's errored trace is tail-retained, and
    # a lane death auto-captures a POD bundle that must hold all of it
    # (validating schema, fault-site events, the typed failure's
    # trace). G2: an armed ``obs.capture`` fault fails the capture
    # path CONTAINED (None return, counted, zero torn ``.tmp``) and
    # the next capture heals with both outcomes journalled.
    subsystem_of["obs.capture"] = "obs"
    inc_tmp = tempfile.mkdtemp(prefix="spfft-chaos-incident-")
    obs.reset_recorder()
    obs.enable_recorder(incident_dir=inc_tmp, min_interval_s=0.0)
    g_plans = [FaultPlan(script="dispatch@1") for _ in range(2)]
    lanes_g = []
    for host, plan_g in zip(("g0", "g1"), g_plans):
        reg = PlanRegistry(store=False)
        reg.put(osig, oplan)
        lanes_g.append((host, ServeExecutor(reg, fault_plan=plan_g)))
    podg = PodFrontend(lanes_g, seed=seed)
    try:
        # transient dispatch faults fire (journalled), requests recover
        good = [vals() for _ in range(3)]
        for i, w in enumerate(good):
            got = np.asarray(
                podg.submit_backward(osig, w).result(timeout=60))
            check(np.array_equal(got, np.asarray(oplan.backward(w))),
                  f"phaseG: request {i} not recovered bit-exact "
                  f"through the armed dispatch fault")
        # a poisoned request fails TYPED and its trace is retained
        try:
            podg.submit_backward(osig, np.zeros(3)).result(timeout=60)
            check(False, "phaseG: poisoned request did not fail")
        except typed:
            pass
        except Exception as exc:
            check(False, f"phaseG: poisoned request failed UNTYPED "
                         f"{type(exc).__name__}: {exc}")
        err_traces = [t for t in obs.retained_traces()
                      if t["reason"] == "error"]
        check(err_traces,
              "phaseG: typed failure's trace was not tail-retained")
        kinds_now = {e["kind"] for e in obs.GLOBAL_JOURNAL.snapshot()}
        check("fault.fired" in kinds_now,
              f"phaseG: armed fault firing not journalled "
              f"({sorted(kinds_now)})")
        # lane death -> debounce-free auto capture of a POD bundle
        podg.kill_host("g1")
        names = [n for n in os.listdir(inc_tmp)
                 if n.startswith("incident-") and n.endswith(".json")]
        check(names, "phaseG: lane death auto-captured no bundle")
        lane_death_bundle = None
        for nme in sorted(names):
            with open(os.path.join(inc_tmp, nme)) as f:
                b = json.load(f)
            bad = obs.validate_bundle(b)
            check(not bad, f"phaseG: bundle {nme} invalid: {bad}")
            if str(b.get("reason", "")).startswith("lane_death"):
                lane_death_bundle = b
        check(lane_death_bundle is not None,
              f"phaseG: no lane_death bundle among {sorted(names)}")
        if lane_death_bundle is not None:
            check(lane_death_bundle["kind"] == "pod",
                  "phaseG: lane-death capture is not a pod bundle")
            tl_kinds = {e["kind"]
                        for e in lane_death_bundle["timeline"]}
            check({"fault.fired", "lane.death"} <= tl_kinds,
                  f"phaseG: pod timeline missing fault/lane-death "
                  f"events ({sorted(tl_kinds)})")
            bundle_errs = [
                t for sub in lane_death_bundle["hosts"].values()
                for t in (sub or {}).get("traces", ())
                if t.get("reason") == "error"]
            check(any(t["trace_id"] == err_traces[0]["trace_id"]
                      for t in bundle_errs) if err_traces else False,
                  "phaseG: typed failure's retained trace missing "
                  "from the auto-captured bundle")
        # the pod keeps serving after the capture
        w = vals()
        got = np.asarray(
            podg.submit_backward(osig, w).result(timeout=60))
        check(np.array_equal(got, np.asarray(oplan.backward(w))),
              "phaseG: post-capture request diverged on the survivor")
        # G2: the capture path itself fails CONTAINED under its fault
        cap_plan = FaultPlan(script="obs.capture@1")
        faults.arm(cap_plan)
        check(obs.capture_incident("chaos-g2") is None,
              "phaseG: faulted capture did not fail contained")
        faults.disarm()
        tally(cap_plan)
        torn = [n for n in os.listdir(inc_tmp) if n.endswith(".tmp")]
        check(not torn,
              f"phaseG: faulted capture left torn files: {torn}")
        # the capture path heals, with BOTH outcomes journalled
        path_g = obs.capture_incident("chaos-g2")
        check(path_g is not None, "phaseG: clean capture failed")
        if path_g is not None:
            with open(path_g) as f:
                healed = json.load(f)
            bad = obs.validate_bundle(healed)
            check(not bad, f"phaseG: healed bundle invalid: {bad}")
            cap_events = [e for e in healed["events"]
                          if e["kind"] == "incident.capture"]
            outcomes = {e["attrs"]["outcome"].split(":")[0]
                        for e in cap_events}
            check({"failed", "written"} <= outcomes,
                  f"phaseG: capture outcomes not journalled "
                  f"({sorted(outcomes)})")
            fired_ev = {e["attrs"]["site"] for e in healed["events"]
                        if e["kind"] == "fault.fired"}
            check("obs.capture" in fired_ev,
                  f"phaseG: obs.capture firing not journalled "
                  f"({sorted(fired_ev)})")
        for plan_g in g_plans:
            tally(plan_g)
        phases["G_flight_recorder"] = {
            "bundles": len(names),
            "retained_error_traces": len(err_traces),
            "stats": obs.recorder_stats()}
    finally:
        faults.disarm()
        podg.close()
        for _, ex_g in lanes_g:
            ex_g.close()
        obs.disable_recorder()
        shutil.rmtree(inc_tmp, ignore_errors=True)
    spans_closed("phaseG")

    subsystems = sorted({subsystem_of[s] for s in fired_sites
                         if s in subsystem_of}
                        | ({"kernel"} if "kernel.launch" in fired_sites
                           else set()))
    check(len(fired_sites) >= 23,
          f"chaos coverage: only {len(fired_sites)} fault sites fired "
          f"({sorted(fired_sites)})")
    check(len(subsystems) >= 10,
          f"chaos coverage: only {len(subsystems)} subsystems hit "
          f"({subsystems})")
    check({"net", "blob", "membership", "obs"} <= set(subsystems),
          f"chaos coverage: wire/recorder subsystems not exercised "
          f"({subsystems})")

    ok = not failures
    print(f"chaos: seed={seed} storms={storms}+{wire_storms} wire "
          f"wave={wave} precision={args.precision}")
    for name, p in phases.items():
        print(f"  {name}: {p}")
    print(f"  sites fired ({len(fired_sites)}): "
          f"{ {s: c for s, c in sorted(fired_sites.items())} }")
    print(f"  subsystems: {subsystems}")
    for msg in failures:
        print(f"FAIL: {msg}", file=sys.stderr)
    result = {
        "metric": f"serve.bench --chaos (5 ladders + {storms} seeded "
                  f"storms + {wire_storms} wire storms + flight-"
                  f"recorder phase over {len(fired_sites)} fault "
                  f"sites)",
        "value": 1 if ok else 0,
        "unit": "ok",
        "chaos": True,
        "ok": ok,
        "seed": seed,
        "failures": failures,
        "phases": phases,
        "fired_sites": fired_sites,
        "subsystems": subsystems,
        "storms": storm_log,
    }
    print(json.dumps(result, default=str))
    if args.output:
        with open(args.output, "w") as f:
            json.dump(result, f, indent=2, default=str)
        print(f"wrote {args.output}")
    return 0 if ok else 1


def main(argv=None) -> int:
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    if args.requests < 1 or args.signatures < 1 or args.threads < 1:
        print("error: --requests, --signatures and --threads must be "
              ">= 1", file=sys.stderr)
        return 2
    if not 0.0 <= args.high_fraction <= 1.0:
        print("error: --high-fraction must be in [0, 1]",
              file=sys.stderr)
        return 2
    if not 0.0 <= args.fault_rate <= 1.0:
        print("error: --fault-rate must be in [0, 1]", file=sys.stderr)
        return 2
    if args.cpu or args.devices > 1:
        # a no-op once the backend is up (the test conftest's virtual
        # 8-device platform stays as-is); on a fresh CPU process it
        # sizes the virtual platform to the requested pool
        from ..utils.platform import force_virtual_cpu_devices
        force_virtual_cpu_devices(max(args.devices, 1))

    if args.smoke:
        return _run_smoke(args)
    if args.fault_smoke:
        return _run_fault_smoke(args)
    if args.chaos is not None:
        return _run_chaos(args)

    import threading

    import jax

    from ..benchmark import cutoff_stick_triplets
    from ..types import TransformType
    from ..utils.platform import platform_summary
    from .executor import ServeExecutor
    from .metrics import ServeMetrics
    from .registry import PlanRegistry

    n = args.dim
    rng = np.random.default_rng(args.seed)

    # S signatures: same grid, S distinct sparsities (distinct sparse
    # sets => distinct digests => distinct plans).
    sparsities = [1.0 - 0.25 * s / max(args.signatures, 1)
                  for s in range(args.signatures)]
    specs = []
    for sp in sparsities:
        triplets = cutoff_stick_triplets(n, n, n, sp, hermitian=False)
        specs.append({"transform_type": TransformType.C2C,
                      "dim_x": n, "dim_y": n, "dim_z": n,
                      "triplets": triplets,
                      "precision": args.precision})

    registry = PlanRegistry()
    t0 = time.perf_counter()
    sigs = registry.warmup(specs, compile=True)
    warmup_s = time.perf_counter() - t0

    # the request trace: per-request signature choice + value array +
    # priority class (deterministic from the seed)
    plans = [registry.get(sig) for sig in sigs]
    trace = []
    for _ in range(args.requests):
        which = int(rng.integers(len(sigs)))
        nv = plans[which].index_plan.num_values
        vals = rng.standard_normal((nv, 2)).astype(np.float32) \
            if args.precision == "single" \
            else (rng.standard_normal(nv)
                  + 1j * rng.standard_normal(nv))
        priority = ("high" if rng.random() < args.high_fraction
                    else "normal")
        trace.append((which, vals, priority))

    # -- serial-loop baseline: a caller WITHOUT the serving layer. It
    # hand-builds its own plan per signature at first use (the 0.35 s
    # cold plan cost the registry exists to amortise — fresh plan
    # objects re-trace/re-compile; jit caches are per plan) and drives
    # every request synchronously. The WARM re-run of the same loop is
    # measured and disclosed too: on the CPU backend a warm tight loop
    # is the dispatch optimum (concurrent in-flight executions thrash
    # the shared intra-op thread pool), so the serving layer's CPU win
    # is plan amortisation — fused batching and the device pool are the
    # TPU-regime levers (multi.FUSED_BATCH_MAX_GRID provenance).
    from ..plan import make_local_plan
    own_plans = {}
    t0 = time.perf_counter()
    for which, vals, _ in trace:
        p = own_plans.get(which)
        if p is None:
            spec = specs[which]
            p = make_local_plan(TransformType.C2C, spec["dim_x"],
                                spec["dim_y"], spec["dim_z"],
                                spec["triplets"],
                                precision=args.precision)
            own_plans[which] = p
        _block(p.backward(vals))
    serial_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    for which, vals, _ in trace:
        _block(own_plans[which].backward(vals))
    warm_loop_s = time.perf_counter() - t0

    # -- executor replay: args.threads submitters, futures gathered
    metrics = ServeMetrics()
    futures = [None] * len(trace)
    pool = jax.devices()
    if args.devices > 0:
        pool = pool[:args.devices]
    # knob resolution: explicit flags > --config artifact > boot env >
    # declared defaults — all through the executor's typed ServeConfig
    cfg = None
    if args.config:
        from ..control import ServeConfig
        cfg = ServeConfig.load(args.config)
    executor = ServeExecutor(registry, batch_window=args.window,
                             max_batch=args.max_batch,
                             max_queue=args.max_queue,
                             batching=not args.no_batching,
                             devices=pool if len(pool) > 1 else None,
                             pin_after=args.pin_after,
                             metrics=metrics, config=cfg)
    window = executor.config.batch_window
    max_batch = executor.config.max_batch
    pin_after = executor.config.pin_after

    # Warm every (signature, device, batch-shape) executable the replay
    # will dispatch, so the measurement reflects a warm server the same
    # way the serial baseline's plans are warm — plus one burst through
    # the queue itself (the dispatcher path has its own first-time
    # costs: thread start, allocator warmup).
    for w, sig in enumerate(sigs):
        executor.prewarm(sig)
        nv = plans[w].index_plan.num_values
        vals = np.zeros((nv, 2), np.float32) \
            if args.precision == "single" else np.zeros(nv, np.complex128)
        for f in [executor.submit(sig, vals)
                  for _ in range(max_batch)]:
            f.result()
    metrics.reset()
    if args.trace_out or args.prom_out:
        # trace the MEASURED replay only (the warm phase's spans would
        # drown it); enabling after warmup also keeps the baseline and
        # warm loop untraced, so the A/B stays clean
        from .. import obs
        obs.enable()
        obs.GLOBAL_TRACER.reset()
    profiling = False
    if args.profile_dir:
        # jax.named_scope phase names (z/exchange/xy) become visible in
        # the captured device profile (open with TensorBoard/XProf)
        try:
            jax.profiler.start_trace(args.profile_dir)
            profiling = True
        except Exception as exc:
            print(f"warning: jax.profiler capture unavailable: {exc}",
                  file=sys.stderr)
    # Fault injection arms AFTER the warm phase: the measured replay
    # degrades, the baseline and warmup stay clean — that's the A/B the
    # acceptance criterion wants (graceful degradation vs collapse).
    fault_plan = None
    if args.fault_rate > 0.0 or args.fault_script:
        from .faults import FaultPlan
        fault_plan = FaultPlan(rate=args.fault_rate, seed=args.seed,
                               scope=args.fault_scope,
                               script=args.fault_script)
        executor.inject_faults(fault_plan)
    # opt-in scrape endpoint + control plane around the MEASURED replay
    metrics_server = None
    mport = _metrics_port(args)
    if mport is not None:
        from ..obs.http import MetricsServer
        metrics_server = MetricsServer(executor=executor, port=mport)
        print(f"metrics endpoint: "
              f"http://127.0.0.1:{metrics_server.start()}/metrics "
              f"(also /healthz, /configz)")
    watchdog = None
    if args.slo:
        from ..control import SLOSpec, SLOWatchdog
        watchdog = SLOWatchdog(metrics, SLOSpec.parse(args.slo))
    controller = control_loop = None
    if args.control:
        from ..control import Controller, ControlLoop
        controller = Controller(executor.config, metrics=metrics,
                                executor=executor, watchdog=watchdog)
        control_loop = ControlLoop(controller,
                                   interval=args.control_interval)
        control_loop.start()
    lock = threading.Lock()
    cursor = [0]

    def submitter():
        while True:
            with lock:
                i = cursor[0]
                if i >= len(trace):
                    return
                cursor[0] += 1
            which, vals, priority = trace[i]
            futures[i] = executor.submit(sigs[which], vals,
                                         priority=priority)

    t0 = time.perf_counter()
    threads = [threading.Thread(target=submitter)
               for _ in range(args.threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    failed_requests = 0
    for f in futures:
        try:
            _block(f.result(timeout=120))
        except Exception:
            failed_requests += 1
    served_s = time.perf_counter() - t0
    if control_loop is not None:
        control_loop.stop()
    executor.close()
    slo_final = watchdog.evaluate() if watchdog is not None else None
    if metrics_server is not None:
        metrics_server.stop()
    if profiling:
        try:
            jax.profiler.stop_trace()
            print(f"wrote jax.profiler trace to {args.profile_dir}")
        except Exception as exc:
            print(f"warning: jax.profiler stop failed: {exc}",
                  file=sys.stderr)

    obs_failures = []
    obs_summary = _finish_obs(args, obs_failures, metrics=metrics,
                              registry=registry)
    for msg in obs_failures:
        print(f"warning: obs: {msg}", file=sys.stderr)

    # the ONE consistent snapshot (ServeMetrics.to_json) — also what
    # obs.prometheus_text renders; bench no longer hand-builds its own
    snap = json.loads(metrics.to_json(registry))
    lat = snap["latency_seconds"]
    by_class = snap["latency_seconds_by_class"]
    overhead = snap["overhead_seconds"]
    throughput = len(trace) / served_s
    serial_throughput = len(trace) / serial_s
    warm_loop_throughput = len(trace) / warm_loop_s
    reg = snap["registry"]

    print(f"signatures={len(sigs)} requests={len(trace)} "
          f"threads={args.threads} dim={n}^3 "
          f"precision={args.precision} "
          f"batching={'off' if args.no_batching else 'on'} "
          f"window={window * 1e3:.1f}ms max_batch={max_batch} "
          f"pin_after={pin_after} device_pool={len(pool)}")
    print(f"warmup: {warmup_s:.2f}s for {len(sigs)} plans "
          f"(registry builds={reg['builds']}, "
          f"bytes={reg['bytes_in_use'] / 1e6:.1f} MB)")
    print(f"serial loop : {serial_s:.3f}s  {serial_throughput:8.1f} "
          f"req/s  (hand-built plans, synchronous — no serving layer)")
    print(f"  warm rerun: {warm_loop_s:.3f}s  {warm_loop_throughput:8.1f} "
          f"req/s  (same loop, plans warm)")
    print(f"executor    : {served_s:.3f}s  {throughput:8.1f} req/s  "
          f"(speedup {throughput / serial_throughput:.2f}x vs serial, "
          f"{throughput / warm_loop_throughput:.2f}x vs warm loop)")
    print(f"latency p50/p95/p99: {lat['p50'] * 1e3:.2f} / "
          f"{lat['p95'] * 1e3:.2f} / {lat['p99'] * 1e3:.2f} ms")
    if args.high_fraction > 0:
        hi, no = by_class["high"], by_class["normal"]
        print(f"  high  lane p50/p99: {hi['p50'] * 1e3:.2f} / "
              f"{hi['p99'] * 1e3:.2f} ms "
              f"({snap['completed_by_class']['high']} requests)")
        print(f"  normal lane p50/p99: {no['p50'] * 1e3:.2f} / "
              f"{no['p99'] * 1e3:.2f} ms "
              f"({snap['completed_by_class']['normal']} requests)")
    print(f"batches: fused={snap['fused_batches']} "
          f"serial={snap['serial_batches']} "
          f"pinned={snap['pinned_batches']} "
          f"padded_rows={snap['padded_rows']} "
          f"histogram={snap['batch_size_histogram']}")
    print(f"orchestration: {overhead['per_bucket'] * 1e3:.3f} ms/bucket "
          f"{overhead['per_request'] * 1e3:.3f} ms/request "
          f"(stage {overhead['stage_total'] * 1e3:.1f} ms + dispatch "
          f"{overhead['dispatch_total'] * 1e3:.1f} ms total)")
    print(f"registry hit-rate: {reg['hit_rate'] * 100:.1f}% "
          f"(hits={reg['hits']} misses={reg['misses']} "
          f"evictions={reg['evictions']})")
    health = snap["health"]
    if fault_plan is not None:
        fstats = fault_plan.stats()
        print(f"faults: injected transient={fstats['fired_transient']} "
              f"permanent={fstats['fired_permanent']} "
              f"by_site={fstats['fired_by_site']}")
        print(f"  recovery: retries={health['retries']} "
              f"exhausted={health['retries_exhausted']} "
              f"bucket_fallbacks={health['bucket_fallbacks']} "
              f"failed_requests={failed_requests}")
        print(f"  pool: quarantines={health['quarantines']} "
              f"probations={health['probations']} "
              f"readmissions={health['readmissions']} "
              f"no_healthy_device={health['no_healthy_device']}")
    print(f"health: {health['state']} "
          f"(crashes={health['dispatcher_crashes']} "
          f"restarts={health['dispatcher_restarts']})")
    control_summary = None
    if controller is not None:
        import dataclasses
        control_summary = {
            "steps": controller.steps,
            "decisions": [dataclasses.asdict(d)
                          for d in controller.decisions()],
            "knobs": executor.config.snapshot(),
        }
        print(f"control: {controller.steps} steps, "
              f"{len(control_summary['decisions'])} decisions; final "
              f"window={executor.config.batch_window * 1e3:.2f}ms "
              f"max_batch={executor.config.max_batch} "
              f"pin_after={executor.config.pin_after} "
              f"pipeline_depth={executor.config.pipeline_depth}")
        for d in control_summary["decisions"]:
            print(f"  step {d['step']}: {d['knob']} {d['old']:g} -> "
                  f"{d['new']:g} ({d['reason']})")
    if slo_final is not None:
        print(f"slo: violations={slo_final['violations'] or 'none'} "
              f"burn={ {k: round(v, 3) for k, v in slo_final['burn'].items()} }")

    result = {
        "metric": f"serve.bench {n}^3 x{len(sigs)} signatures, "
                  f"{len(trace)} requests, {args.threads} threads "
                  f"(p50={lat['p50'] * 1e3:.2f}ms "
                  f"p95={lat['p95'] * 1e3:.2f}ms "
                  f"p99={lat['p99'] * 1e3:.2f}ms, "
                  f"fused_batches={snap['fused_batches']}, "
                  f"pinned_batches={snap['pinned_batches']}, "
                  f"padded_rows={snap['padded_rows']}, "
                  f"registry_hit_rate={reg['hit_rate']:.3f})",
        "value": round(throughput, 3),
        "unit": "req/s",
        "throughput_rps": round(throughput, 3),
        "serial_throughput_rps": round(serial_throughput, 3),
        "warm_loop_throughput_rps": round(warm_loop_throughput, 3),
        "speedup_vs_serial": round(throughput / serial_throughput, 3),
        "speedup_vs_warm_loop": round(
            throughput / warm_loop_throughput, 3),
        "registry_hit_rate": round(reg["hit_rate"], 4),
        "high_fraction": args.high_fraction,
        "fault_rate": args.fault_rate,
        "fault_script": args.fault_script,
        "failed_requests": failed_requests,
        "faults": (fault_plan.stats() if fault_plan is not None
                   else None),
        "obs": obs_summary,
        "obs_failures": obs_failures,
        "control": control_summary,
        "slo": slo_final,
        "serve_metrics": snap,
        "platform": platform_summary(),
    }
    print(json.dumps(result))
    if args.output:
        with open(args.output, "w") as f:
            json.dump(result, f, indent=2)
        print(f"wrote {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

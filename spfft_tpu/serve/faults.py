"""Deterministic fault injection for the serving layer.

The failure-handling machinery in ``ServeExecutor`` (bucket-failure
isolation, bounded retries, device quarantine, the crash-proof dispatch
supervisor) is only trustworthy if every path is TESTABLE without real
hardware faults. This module is that seam: a :class:`FaultPlan` is an
injectable oracle the executor consults at four named sites of its
dispatch pipeline —

* ``stage``       — host-side payload staging of a fused bucket
* ``dispatch``    — the executable dispatch call (fused or serial;
  carries the pool-device index when a pool is in use)
* ``materialise`` — ``block_until_ready`` on a bucket's results
* ``loop``        — top of each dispatch-loop iteration (crashing here
  exercises the supervisor, not the per-bucket error handling)

A firing check raises :class:`InjectedFault`, which flows through the
SAME except-paths a real XLA/runtime failure would — nothing in the
executor special-cases injected errors beyond their transient/permanent
tag. Faults fire two ways, both deterministic:

* **scripted** — ``"dispatch@3"`` fails the 3rd dispatch check,
  ``"device1@*:permanent"`` fails every check on pool device 1,
  ``"loop@1"`` crashes the first loop iteration. Site call counters are
  per-site (and per-device), so a script replays identically on an
  identical sequence of checks.
* **probabilistic** — ``rate`` per-check probability from a seeded RNG
  (``random.Random(seed)``), optionally restricted to one ``scope``
  site or ``"device:N"``. Same seed + same check sequence = same fault
  sequence, which is what lets ``serve.bench --fault-rate`` measure
  degradation instead of just asserting it.

Transient-vs-permanent classification (:func:`is_transient`) drives the
executor's retry policy: injected faults carry an explicit ``transient``
flag; real exceptions classify by an explicit ``transient`` attribute
when present, then by type (``TimeoutError``), then by the gRPC-style
status markers XLA runtime errors embed (``RESOURCE_EXHAUSTED``,
``UNAVAILABLE``, ...). Everything else is permanent — retrying a shape
error or a poisoned payload would just burn device time twice.
"""

from __future__ import annotations

import random
import re
import threading
from typing import Dict, List, Optional, Tuple

from ..errors import (DuplicateIndicesError, InvalidIndicesError,
                      InvalidParameterError, ServeError)

#: The executor's named fault-check sites.
SITES = ("stage", "dispatch", "materialise", "loop")

#: Substrings of runtime error text treated as transient — the
#: retryable subset of the gRPC status codes XLA/PJRT embed in
#: RuntimeError messages (device OOM under fragmentation, a briefly
#: unreachable device, a preempted collective).
TRANSIENT_MARKERS = ("RESOURCE_EXHAUSTED", "UNAVAILABLE",
                     "DEADLINE_EXCEEDED", "ABORTED")


class InjectedFault(ServeError):
    """A failure raised by a :class:`FaultPlan` check. Carries the
    ``transient`` classification the executor's retry policy reads and
    the ``device_attributed`` classification its quarantine accounting
    reads (True by default — injection simulates infrastructure faults;
    the ``poison`` script kind injects request-attributed ones);
    otherwise handled exactly like any runtime failure."""

    def __init__(self, message: str, transient: bool = True,
                 device_attributed: bool = True):
        super().__init__(message)
        self.transient = transient
        self.device_attributed = device_attributed


def is_transient(exc: BaseException) -> bool:
    """Whether ``exc`` warrants the one bounded retry. An explicit
    ``transient`` attribute wins (injected faults, or any runtime that
    tags its errors); ``TimeoutError`` and XLA runtime errors carrying a
    retryable gRPC status marker are transient; everything else —
    shape/type errors, poisoned payloads, logic bugs — is permanent."""
    tagged = getattr(exc, "transient", None)
    if tagged is not None:
        return bool(tagged)
    if isinstance(exc, TimeoutError):
        return True
    text = str(exc)
    return any(marker in text for marker in TRANSIENT_MARKERS)


#: Exception types that indict the REQUEST, not the device it ran on:
#: shape/type/index errors (a poisoned payload fails identically on
#: every healthy device) and the library's own validation errors.
REQUEST_ERROR_TYPES = (TypeError, ValueError, IndexError, KeyError,
                       InvalidParameterError, InvalidIndicesError,
                       DuplicateIndicesError)


def attributes_device(exc: BaseException) -> bool:
    """Whether a failure should count against the DEVICE it ran on
    (quarantine accounting) rather than the request that triggered it.
    An explicit ``device_attributed`` attribute wins (injected faults,
    or a runtime that tags its errors); request-shaped errors
    (:data:`REQUEST_ERROR_TYPES` — a poisoned payload raises the same
    error on every healthy device) indict the request; everything else
    — XLA runtime errors, timeouts, unknown failures — charges the
    device, which preserves the round-8 quarantine behaviour for real
    hardware faults. This is the classifier that stops a pure
    poisoned-request flood from spuriously quarantining a healthy
    device (ROADMAP round-11 follow-on)."""
    tagged = getattr(exc, "device_attributed", None)
    if tagged is not None:
        return bool(tagged)
    if isinstance(exc, REQUEST_ERROR_TYPES):
        return False
    return True


_ENTRY_RE = re.compile(
    r"^(?P<site>[a-z]+|device\d+)@(?P<nth>\d+|\*)(?::(?P<kind>\w+))?$")


def _parse_entry(spec: str) -> Tuple[str, Optional[int], str]:
    """One script entry ``SITE@N[:KIND]`` -> (counter key, nth-or-None
    for always, kind). SITE is a check site or ``deviceK``; ``N`` is
    the 1-based call index of that counter, ``*`` fires on every call;
    KIND is ``transient`` (default), ``permanent`` (both
    device-attributed) or ``poison`` (permanent AND request-attributed
    — simulates a bad payload, exercising the quarantine-attribution
    seam)."""
    m = _ENTRY_RE.match(spec.strip())
    if not m:
        raise InvalidParameterError(
            f"bad fault-script entry {spec!r} (want SITE@N[:KIND], e.g. "
            f"'dispatch@3', 'device1@*:permanent', 'loop@1')")
    site = m.group("site")
    if site not in SITES and not site.startswith("device"):
        raise InvalidParameterError(
            f"unknown fault site {site!r} (sites: {SITES} or deviceK)")
    nth = None if m.group("nth") == "*" else int(m.group("nth"))
    if nth is not None and nth < 1:
        raise InvalidParameterError("fault-script call index is 1-based")
    kind = m.group("kind") or "transient"
    if kind not in ("transient", "permanent", "poison"):
        raise InvalidParameterError(
            f"fault kind must be transient|permanent|poison, "
            f"got {kind!r}")
    return site, nth, kind


class FaultPlan:
    """Deterministic fault-injection oracle for ``ServeExecutor``.

    ``script`` is an iterable of ``SITE@N[:KIND]`` entries (or one
    comma-separated string); ``rate`` adds seeded per-check transient
    faults, optionally restricted to ``scope`` (a site name or
    ``"device:N"``). Thread-safe: checks run on the dispatcher thread,
    stats reads come from anywhere.
    """

    def __init__(self, rate: float = 0.0, seed: int = 0,
                 scope: Optional[str] = None, script=None):
        if not 0.0 <= rate <= 1.0:
            raise InvalidParameterError("fault rate must be in [0, 1]")
        if scope is not None:
            key = scope.replace("device:", "device")
            if key not in SITES and not (key.startswith("device")
                                         and key[6:].isdigit()):
                raise InvalidParameterError(
                    f"bad fault scope {scope!r} (sites: {SITES} or "
                    f"'device:N')")
            scope = key
        if isinstance(script, str):
            script = [s for s in script.split(",") if s.strip()]
        self._rate = float(rate)
        self._rng = random.Random(seed)  #: guarded by _lock
        self._scope = scope
        self._script: List[Tuple[str, Optional[int], str]] = \
            [_parse_entry(s) for s in (script or [])]
        self._lock = threading.Lock()
        self._calls: Dict[str, int] = {}  #: guarded by _lock
        #: guarded by _lock
        self._fired: Dict[str, int] = {"transient": 0, "permanent": 0,
                                       "poison": 0}
        self._fired_by_site: Dict[str, int] = {}  #: guarded by _lock

    def _in_scope(self, site: str, dev_key: Optional[str]) -> bool:
        if self._scope is None:
            return site != "loop"  # rate faults never crash the loop
        return self._scope == site or self._scope == dev_key

    def check(self, site: str, device: Optional[int] = None) -> None:
        """One pipeline checkpoint: increments the ``site`` counter (and
        the ``deviceN`` counter when a pool device index is given) and
        raises :class:`InjectedFault` when a script entry or the seeded
        rate says this call fails. No-op otherwise."""
        with self._lock:
            n = self._calls[site] = self._calls.get(site, 0) + 1
            dev_key = dn = None
            if device is not None:
                dev_key = f"device{device}"
                dn = self._calls[dev_key] = self._calls.get(dev_key,
                                                           0) + 1
            fire = None
            for key, nth, kind in self._script:
                hit = (key == site and (nth is None or nth == n)) or \
                      (key == dev_key and (nth is None or nth == dn))
                if hit:
                    fire = kind
                    break
            if fire is None and self._rate > 0.0 \
                    and self._in_scope(site, dev_key):
                if self._rng.random() < self._rate:
                    fire = "transient"
            if fire is None:
                return
            self._fired[fire] += 1
            self._fired_by_site[site] = \
                self._fired_by_site.get(site, 0) + 1
        where = site if device is None else f"{site} (device {device})"
        raise InjectedFault(f"injected {fire} fault at {where}",
                            transient=fire == "transient",
                            device_attributed=fire != "poison")

    def stats(self) -> Dict:
        """Counter snapshot: checks seen and faults fired, per site."""
        with self._lock:
            return {
                "rate": self._rate,
                "scope": self._scope,
                "script_entries": len(self._script),
                "checks": dict(self._calls),
                "fired_transient": self._fired["transient"],
                "fired_permanent": self._fired["permanent"],
                "fired_poison": self._fired["poison"],
                "fired_by_site": dict(self._fired_by_site),
            }

"""Compatibility shim: fault injection is now package-level.

Round 8 introduced deterministic fault injection here, scoped to the
serving executor's four check sites. The seam since outgrew the
serving layer — plan builds, the artifact store, the registry, fused
kernels and the distributed exchange all consult the same oracle — so
the implementation lives in :mod:`spfft_tpu.faults`. This module
re-exports the public surface so existing imports
(``from spfft_tpu.serve.faults import FaultPlan``) keep working.
"""

from __future__ import annotations

from ..faults import (KINDS, PERSISTENT_DISK_ERRNOS, REQUEST_ERROR_TYPES,
                      SITES, TRANSIENT_MARKERS, FaultPlan,
                      InjectedDiskFull, InjectedFault, arm, armed,
                      attributes_device, check_site, disarm,
                      is_persistent_disk_error, is_transient)

__all__ = [
    "FaultPlan", "InjectedFault", "InjectedDiskFull",
    "SITES", "KINDS", "TRANSIENT_MARKERS", "REQUEST_ERROR_TYPES",
    "PERSISTENT_DISK_ERRNOS",
    "is_transient", "attributes_device", "is_persistent_disk_error",
    "arm", "armed", "disarm", "check_site",
]

"""Concurrent batching executor: futures in, fused batches out.

The reference's throughput lever for many independent transforms is its
multi-transform scheduler — hand-interleaved phases of N transforms
(reference: src/spfft/multi_transform_internal.hpp:47-145), reproduced
here as ``spfft_tpu.multi``. This module turns that primitive into a
request-driven serving layer: callers ``submit(signature, values)`` from
any number of threads and get ``concurrent.futures.Future``s back; a
single dispatcher thread buckets same-signature requests and executes
full buckets through the plan's fused batched executables (the
``multi.py`` fused path — one vmapped dispatch for B requests),
stragglers through the ordinary serial path.

The dispatch path is built for hardware-speed serving:

* **Per-signature pending shards** — requests land in a shard keyed by
  ``(signature, kind, scaling)``; bucket formation pops one shard's
  lanes instead of re-scanning one global queue per take (the PR-1
  structure, O(queue) per bucket).
* **Priority lanes + EDF** — ``submit(..., priority="high")`` enters a
  shard's high lane, served before ANY normal-lane work; within each
  lane requests order earliest-deadline-first (deadline-less requests
  keep FIFO order behind every deadlined one). A forming normal bucket
  closes its batching window early when a high-priority request arrives
  for another signature or a queued deadline is about to expire.
* **Adaptive batch-shape pinning** — a per-shard observer watches
  fused bucket sizes; once the same size repeats ``pin_after``
  consecutive times, that EXACT shape is pinned (per-signature LRU,
  ``max_pinned_shapes`` entries) and buckets of that size dispatch with
  ZERO pad rows. One bucket before the pin lands, the exact-shape
  executable compiles on a background thread (prewarm-on-pin), so the
  first pinned dispatch hits a warm jit cache. Shape churn never pins
  and falls back to the pow2 ladder (``multi.planned_batch_size``).
* **Reusable staging buffers + double-buffered pipelining** — fused
  buckets stack into preallocated per-(shard, shape) host buffers, and
  the in-flight window is one deeper than the device pool so the host
  stacks bucket N+1 while the devices execute bucket N.

Failure is a first-class surface (the reference's 16-type exception
hierarchy + cross-rank mismatch checks, exceptions.hpp /
grid_internal.cpp:148-167, carried to the serving layer):

* **Bucket-failure isolation** — a fused bucket that raises (dispatch
  or materialisation) falls back to per-request serial re-execution, so
  one poisoned request fails alone and its healthy co-batched neighbors
  still return bit-exact results. Each request draws on a bounded
  PER-PRIORITY retry budget (``retry_budget``; default high=2,
  normal=1, so SLO-critical work rides out one more transient):
  transient failures (``faults.is_transient``) that persist through the
  budget surface as ``RetryExhaustedError`` carrying the cause;
  permanent failures surface immediately as themselves.
* **Device quarantine** — per-device consecutive-failure accounting on
  the round-robin pool; a device crossing ``quarantine_after`` failures
  is quarantined with exponential-backoff probation (one canary request
  re-admits it on success, doubles the backoff on failure). An empty
  pool fails requests with ``NoHealthyDeviceError`` instead of
  dispatching into a known-sick device.
* **Crash-proof dispatch** — the dispatcher thread runs under a
  supervisor: an exception escaping the per-bucket handling fails that
  bucket's futures, flushes in-flight work, and restarts the loop up to
  ``max_dispatch_restarts`` times; past the budget every queued future
  fails with ``ExecutorCrashedError``. A crash can degrade the service
  but can never silently strand a caller on a forever-pending future.
  Executor health (healthy/degraded/draining/failed) is exposed via
  ``ServeMetrics.health()`` / :meth:`ServeExecutor.health`.
* **Deterministic fault injection** — every path above is driven
  through ``faults.FaultPlan`` checkpoints (stage / dispatch /
  materialise / loop, per pool device), so the whole failure surface is
  tier-1-testable on CPU and measurable via ``serve.bench
  --fault-rate``.

Correctness contract: any interleaving of concurrent requests produces
results BIT-IDENTICAL to running each request alone on its plan. Three
structural facts make this hold: (1) requests only share a bucket when
their signatures are equal, and equal signatures resolve to the same
plan object (registry invariant); (2) the fused batched pipeline is the
vmapped form of the serial pipeline over identical static tables — vmap
rows are independent, so pad rows (repeats of row 0) and the CHOICE of
batch shape (pinned exact vs ladder) cannot perturb the live rows;
(3) staged host buffers carry exactly the per-row coerced layout
(``plan.batch_row_template``) at the plan's own dtype. The failure
paths preserve the contract: recovery re-executions run the SAME serial
pipeline the oracle does, so a retried request's result is bit-identical
to its serial execution. Verified by the tier-1 concurrency fuzzes
(tests/test_serve_executor.py, tests/test_serve_faults.py).

Observability (``spfft_tpu.obs``, round 10): when tracing is enabled,
every sampled request carries a ``RequestTrace`` — spans for all eight
pipeline stages (submit / queue-wait / bucket-formation / stage /
dispatch / device-execute / materialise / resolve) on per-lane and
per-device tracks, retry/fallback/quarantine annotations, and a
zero-unclosed-spans guarantee: every resolution path (success, typed
failure, crash sweep, deadline expiry, close) settles the request's
whole trace, error-typed on failure. The disabled path is one
module-global boolean read per checkpoint (measured ≤ noise,
BENCHMARKS.md "Round-10").

Flow control is explicit and bounded: a fixed-capacity queue whose
overflow REJECTS with ``QueueFullError`` (after reaping already-expired
deadlined requests, so a queue full of dead work never rejects live
work), per-request deadlines that expire queued work with
``DeadlineExpiredError`` before it wastes device time, and
``batching=False`` (or a fusion-ineligible regime) degrading gracefully
to serial per-request dispatch.

Control plane (``spfft_tpu.control``, round 11): every tunable above —
batch window, bucket cap, queue bound, pin policy, pipeline depth,
quarantine policy — lives in ONE typed, bounds-clamped
:class:`~spfft_tpu.control.config.ServeConfig` the executor reads
through on every use. A feedback controller can hot-swap any knob
under the config's lock (the change applies from the next bucket, and
the correctness contract above makes any mid-stream retune bit-exact);
every accepted change is recorded as a Prometheus
``spfft_control_decisions_total`` tick and a ``control.retune`` trace
annotation. The executor feeds the controller's signals through
``ServeMetrics``: per-request queue waits and per-bucket device-execute
times land in recent-window reservoirs next to the round-7 pad/batch
counters. Boot-time configuration loads from the
``SPFFT_TPU_SERVE_CONFIG`` artifact (the offline auto-tuner's output).
"""

from __future__ import annotations

import collections
import heapq
import itertools
import math
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import obs as _obs
from ..control.config import KNOB_SPECS, ServeConfig
from ..errors import (DeadlineExpiredError, DistributedPlanUnsupportedError,
                      ExecuteTimeoutError, ExecutorCrashedError,
                      InvalidParameterError, NoHealthyDeviceError,
                      QueueFullError, RetryExhaustedError, ServeError)
from ..multi import fusion_eligible, planned_batch_size
from ..plan import TransformPlan
from ..types import Scaling
from .faults import FaultPlan, attributes_device, is_transient
from .metrics import ServeMetrics
from .registry import PlanRegistry, PlanSignature

#: Boot-prewarm manifest location: when set (and no explicit
#: ``prewarm_manifest`` argument is given), a constructing executor
#: warm-loads every listed plan artifact — and compiles it — BEFORE its
#: dispatcher thread starts, so a replacement process joins the pool
#: fully warm (docs/artifact_cache.md "Prewarm workflow"). The store
#: keeps the same manifest LIVE: every spill merges its entry in
#: (``PlanArtifactStore.append_manifest_entry``); the canonical
#: spelling lives there.
from .store import PLAN_MANIFEST_ENV  # noqa: E402  (re-export)

# Knob defaults live in ONE place since round 11: the control plane's
# KNOB_SPECS (spfft_tpu/control/config.py), which also declares each
# knob's hard bounds and driving telemetry signal. The aliases below
# keep the historical import surface (bench/tests read these) — the
# measured provenance of the values (round-7 window/pinning retunes,
# round-8 quarantine policy) is documented on the specs.
DEFAULT_BATCH_WINDOW = KNOB_SPECS["batch_window"].default
DEFAULT_MAX_BATCH = KNOB_SPECS["max_batch"].default
DEFAULT_MAX_QUEUE = KNOB_SPECS["max_queue"].default
DEFAULT_PIN_AFTER = KNOB_SPECS["pin_after"].default
DEFAULT_MAX_PINNED = KNOB_SPECS["max_pinned_shapes"].default
DEFAULT_QUARANTINE_AFTER = KNOB_SPECS["quarantine_after"].default
DEFAULT_QUARANTINE_BACKOFF = KNOB_SPECS["quarantine_backoff"].default

#: Ceiling on the exponential probation backoff.
QUARANTINE_BACKOFF_CAP = 60.0

#: Dispatch-loop restarts the supervisor attempts before declaring the
#: executor failed and rejecting everything queued.
DEFAULT_MAX_RESTARTS = 3

_PRIORITIES = ("normal", "high")

#: Per-priority bounded-retry budget for transient failures (ROADMAP
#: fault-tolerance follow-on: the retry budget was a flat 1). High-lane
#: requests are the ones callers marked latency/SLO-critical, so they
#: get one more shot at riding out a transient than normal work; a
#: normal request still gets the single bounded retry of round 8.
#: Override per executor with ``retry_budget={"normal": n, "high": m}``
#: (missing classes fall back to these defaults; 0 disables retries for
#: a class — first failure surfaces immediately).
DEFAULT_RETRY_BUDGET = {"normal": 1, "high": 2}


class _Request:
    __slots__ = ("key", "plan", "kind", "values", "scaling", "deadline",
                 "priority", "seq", "future", "enqueued_at", "trace")

    def __init__(self, key, plan, kind, values, scaling, deadline,
                 priority, seq):
        self.key = key
        self.plan = plan
        self.kind = kind
        self.values = values
        self.scaling = scaling
        self.deadline = deadline
        self.priority = priority
        self.seq = seq
        self.future: Future = Future()
        self.enqueued_at = time.monotonic()
        #: obs.RequestTrace when tracing is on AND this request was
        #: sampled; None otherwise (the disabled-path cost is this
        #: attribute staying None).
        self.trace = None


def _dev_track(slot) -> str:
    """Trace track name for a pool slot (one track per pool device)."""
    return f"device:{slot.index}" if slot is not None else "device:0"


class _BucketTrace:
    """Span bookkeeping for one dispatched bucket. Bucket-level stages
    (formation/stage/dispatch/device-execute/materialise) are recorded
    ONCE per bucket — parented under the first traced member's request
    root, carrying every member's trace id in ``member_trace_ids`` — so
    an 8-row fused bucket costs 5 spans, not 40. ``end_all`` closes
    whatever is still open with an error status; every failure path in
    the executor calls it BEFORE resolving member futures, so bucket
    spans always nest inside their parent request span."""

    __slots__ = ("tracer", "trace_id", "parent", "ids", "open")

    def __init__(self, tracer, traced):
        first = traced[0].trace
        self.tracer = tracer
        self.trace_id = first.trace_id
        self.parent = first.root
        self.ids = [r.trace.trace_id for r in traced]
        self.open = {}

    def begin(self, name, track=None, args=None):
        a = {"member_trace_ids": list(self.ids)}
        if args:
            a.update(args)
        # span: closed-by(_BucketTrace.end_all)
        self.open[name] = self.tracer.begin(
            name, trace_id=self.trace_id, parent=self.parent,
            track=track, args=a)

    def end(self, name, status="ok", error=None):
        sp = self.open.pop(name, None)
        if sp is not None:
            self.tracer.finish(sp, status=status, error=error)

    def end_all(self, status="ok", error=None):
        for name in list(self.open):
            self.end(name, status, error)


class _Shard:
    """Pending work + batch-shape observer for one (signature, kind,
    scaling) key. Lanes are heaps of ``(deadline-or-inf, seq, request)``
    — EDF within the lane, FIFO among deadline-less requests. The shard
    survives idle periods so its observer state (and the signature's
    pinned shapes) persist across traffic gaps."""

    __slots__ = ("key", "plan", "high", "normal", "last_size", "streak",
                 "row_template", "template_ready")

    def __init__(self, key, plan):
        self.key = key
        self.plan = plan
        self.high: List[Tuple[float, int, _Request]] = []
        self.normal: List[Tuple[float, int, _Request]] = []
        self.last_size = 0
        self.streak = 0
        self.row_template = None
        self.template_ready = False

    def pending(self) -> bool:
        return bool(self.high or self.normal)

    def head_rank(self):
        """Scheduling rank of this shard's most urgent request:
        ``(lane, deadline-or-inf, seq)`` — high lane beats normal,
        then EDF, then arrival order. None when empty."""
        if self.high:
            return (0, self.high[0][0], self.high[0][1])
        if self.normal:
            return (1, self.normal[0][0], self.normal[0][1])
        return None


class _DeviceSlot:
    """Health accounting for one pool device: consecutive-failure count,
    quarantine state and the exponential probation backoff. Mutated only
    under the executor's pool lock."""

    __slots__ = ("device", "index", "failures", "state", "until",
                 "backoff")

    def __init__(self, device, index, backoff):
        self.device = device
        self.index = index
        self.failures = 0
        self.state = "healthy"   # healthy | quarantined | probation
        self.until = 0.0         # when a quarantined slot is probe-able
        self.backoff = backoff


class ServeExecutor:
    """One dispatcher thread over bounded per-signature request shards.

    ``registry`` resolves signatures to plans (requests for unknown
    signatures are rejected at submit time — a server warms its shapes
    up front; see ``PlanRegistry.warmup``). Use as a context manager or
    call :meth:`close` to drain and stop.

    ``autostart=False`` defers the dispatcher thread until
    :meth:`start` — used by tests (and pre-warm scripts) to stage a
    queue deterministically before any dispatch happens.

    Failure knobs: ``quarantine_after`` / ``quarantine_backoff`` control
    the device-pool quarantine, ``max_dispatch_restarts`` bounds the
    crash supervisor, ``retry_budget`` sets the per-priority transient
    retry budget (``{"normal": 1, "high": 2}`` by default — the high
    lane gets one more attempt), ``fault_plan`` arms deterministic
    fault injection (see :mod:`~spfft_tpu.serve.faults`),
    ``prewarm_on_pin`` toggles the background exact-shape compile one
    bucket before a pin lands.
    """

    def __init__(self, registry: PlanRegistry,
                 batch_window: Optional[float] = None,
                 max_batch: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 batching: bool = True,
                 devices=None,
                 metrics: Optional[ServeMetrics] = None,
                 pin_after: Optional[int] = None,
                 max_pinned_shapes: Optional[int] = None,
                 pipeline_depth: Optional[int] = None,
                 fault_plan: Optional[FaultPlan] = None,
                 quarantine_after: Optional[int] = None,
                 quarantine_backoff: Optional[float] = None,
                 max_dispatch_restarts: int = DEFAULT_MAX_RESTARTS,
                 retry_budget: Optional[Dict[str, int]] = None,
                 prewarm_on_pin: bool = True,
                 autostart: bool = True,
                 config: Optional[ServeConfig] = None,
                 prewarm_manifest: Optional[str] = None):
        # Knob resolution (round 11): every tunable lives in ONE typed
        # ServeConfig the control plane owns. Explicit constructor
        # arguments are validated (the historical error contract) and
        # override the config; None defers to the config's value —
        # which is the declared default, the SPFFT_TPU_SERVE_CONFIG
        # boot artifact, or whatever a live controller has retuned it
        # to. The dispatcher reads the knobs through the config on
        # every use, so a controller's set() applies from the next
        # bucket (hot-swap under the config's lock).
        if max_batch is not None and max_batch < 1 \
                or max_queue is not None and max_queue < 1:
            raise InvalidParameterError(
                "max_batch and max_queue must be >= 1")
        if pipeline_depth is not None and pipeline_depth < 1:
            raise InvalidParameterError("pipeline_depth must be >= 1")
        if pin_after is not None and pin_after < 0 \
                or max_pinned_shapes is not None \
                and max_pinned_shapes < 1:
            raise InvalidParameterError(
                "pin_after must be >= 0 and max_pinned_shapes >= 1")
        if quarantine_after is not None and quarantine_after < 0 \
                or quarantine_backoff is not None \
                and quarantine_backoff <= 0.0 \
                or max_dispatch_restarts < 0:
            raise InvalidParameterError(
                "quarantine_after and max_dispatch_restarts must be "
                ">= 0, quarantine_backoff > 0")
        self.config = config if config is not None else ServeConfig.boot()
        overrides = {
            "batch_window": batch_window, "max_batch": max_batch,
            "max_queue": max_queue, "pin_after": pin_after,
            "max_pinned_shapes": max_pinned_shapes,
            "pipeline_depth": pipeline_depth,
            "quarantine_after": quarantine_after,
            "quarantine_backoff": quarantine_backoff,
        }
        for name, value in overrides.items():
            if value is not None:
                self.config.set(name, value, source="init",
                                reason="constructor override")
        budget = dict(DEFAULT_RETRY_BUDGET)
        if retry_budget:
            unknown = set(retry_budget) - set(_PRIORITIES)
            if unknown:
                raise InvalidParameterError(
                    f"retry_budget classes must be in {_PRIORITIES}, "
                    f"got {sorted(unknown)}")
            if any(int(v) < 0 for v in retry_budget.values()):
                raise InvalidParameterError(
                    "retry_budget values must be >= 0")
            budget.update({k: int(v) for k, v in retry_budget.items()})
        self._retry_budget = budget
        self.registry = registry
        self.metrics = metrics if metrics is not None else ServeMetrics()
        # The device pool: ``None`` keeps every execution on the default
        # placement (single-accelerator process); ``"all"`` spreads
        # requests round-robin over every visible device — fused buckets
        # land whole on one device, serial buckets fan their requests
        # across the pool. On a multi-chip host this is the throughput
        # multiplier a registry + one queue cannot provide on their own.
        if devices == "all":
            import jax
            devices = list(jax.devices())
        self._devices = list(devices) if devices else [None]
        self._rotor = 0          #: guarded by _pool_lock
        self._auto_extra: Optional[int] = None
        self._batching = bool(batching)
        self._faults = fault_plan
        self._max_restarts = int(max_dispatch_restarts)
        self._prewarm_on_pin = bool(prewarm_on_pin)
        self._pool_lock = threading.Lock()
        #: guarded by _pool_lock
        self._slots = [_DeviceSlot(d, i, self._q_backoff)
                       for i, d in enumerate(self._devices)]
        self._shards: Dict[tuple, _Shard] = {}  #: guarded by _cv
        self._pending = 0        #: guarded by _cv
        self._high_pending = 0   #: guarded by _cv
        # GIL-atomic arrival counter: requests are stamped BEFORE the
        # queue lock so Future/request construction never extends the
        # lock hold; heap ties only need uniqueness + rough arrival
        # order, not lock-exact monotonicity
        self._seq = itertools.count(1)
        # per-signature pinned exact batch shapes (LRU); dispatcher
        # thread only, no lock needed
        self._pins: Dict[PlanSignature,
                         "collections.OrderedDict[int, None]"] = {}
        # staging buffer free-lists, keyed (shard key, batch shape);
        # dispatcher thread only
        self._staging: Dict[tuple, List[np.ndarray]] = {}
        # prewarm-on-pin background compiles, keyed (shard key, shape)
        self._prewarm_threads: Dict[tuple, threading.Thread] = {}
        # supervisor state: buckets the dispatcher holds outside the
        # shards (forming + in-flight) so a crash can fail their
        # futures instead of stranding them in dead local variables
        self._inflight: "collections.deque" = collections.deque()
        self._forming: Optional[List[_Request]] = None
        self._restarts = 0       #: guarded by _cv
        self._failed = False     #: guarded by _cv
        self._cv = threading.Condition()
        self._closed = False     #: guarded by _cv
        self._thread: Optional[threading.Thread] = None  #: guarded by _cv
        # zero-cold-start boot: prewarm every manifest-listed plan
        # artifact (load + compile) BEFORE the dispatcher accepts work
        import os as _os
        manifest = prewarm_manifest \
            if prewarm_manifest is not None \
            else _os.environ.get(PLAN_MANIFEST_ENV)
        if manifest:
            self.registry.warmup_manifest(manifest, compile=True)
        if autostart:
            self.start()

    # -- knobs (hot-swappable: every read goes through the config) ---------
    @property
    def _batch_window(self) -> float:
        return self.config.batch_window

    @property
    def _max_batch(self) -> int:
        return self.config.max_batch

    @property
    def _max_queue(self) -> int:
        return self.config.max_queue

    @property
    def _pin_after(self) -> int:
        return self.config.pin_after

    @property
    def _max_pinned(self) -> int:
        return self.config.max_pinned_shapes

    @property
    def _pipeline_depth(self) -> Optional[int]:
        depth = self.config.pipeline_depth
        return None if depth == 0 else depth

    @property
    def _q_after(self) -> int:
        return self.config.quarantine_after

    @property
    def _q_backoff(self) -> float:
        return self.config.quarantine_backoff

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """Start the supervised dispatcher thread (idempotent)."""
        with self._cv:
            if self._closed:
                raise ServeError("executor is closed")
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run_dispatcher,
                    name="spfft-serve-dispatcher", daemon=True)
                self._thread.start()
        self._push_health()

    def close(self, drain: bool = True) -> None:
        """Stop accepting work and shut the dispatcher down. With
        ``drain`` (default) queued requests execute first; otherwise
        they fail with ``ServeError``. Either way, EVERY still-pending
        future is resolved before close returns — no caller is ever
        left blocked on a future that cannot complete."""
        dropped: List[_Request] = []
        with self._cv:
            if self._closed:
                return
            self._closed = True
            if not drain or self._failed:
                for shard in self._shards.values():
                    for lane in (shard.high, shard.normal):
                        dropped.extend(req for _, _, req in lane)
                        lane.clear()
                self._pending = 0
                self._high_pending = 0
            self._cv.notify_all()
            thread = self._thread
        self._push_health()
        self._fail_requests(dropped,
                            ServeError("executor closed before dispatch"))
        if thread is None:
            # never started: drain synchronously so no future is left
            # forever-pending
            self._drain_once()
        else:
            thread.join()
        # defensive final sweep — anything a crashed/raced dispatcher
        # left behind resolves with a typed error rather than hanging
        self._fail_all_pending(ServeError("executor closed"))

    def __enter__(self) -> "ServeExecutor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- fault/health plumbing ---------------------------------------------
    def inject_faults(self, fault_plan: Optional[FaultPlan]) -> None:
        """Arm (replace, or clear with None) the fault-injection plan.
        The deterministic test/bench seam — production servers leave it
        unset and every check is a no-op attribute read."""
        self._faults = fault_plan

    def _check_fault(self, site: str, device: Optional[int] = None):
        plan = self._faults
        if plan is not None:
            plan.check(site, device)

    def _push_health(self) -> None:
        """Recompute the lifecycle state and push it into the metrics
        sink: failed > draining > degraded (restarted dispatcher or any
        non-healthy pool device) > healthy."""
        with self._cv:
            failed, closed = self._failed, self._closed
            restarts = self._restarts
        if failed:
            state = "failed"
        elif closed:
            state = "draining"
        else:
            with self._pool_lock:
                sick = any(s.state != "healthy" for s in self._slots)
            state = "degraded" if (restarts or sick) else "healthy"
        self.metrics.record_health(state)

    def health(self) -> Dict:
        """The :meth:`ServeMetrics.health` snapshot plus live per-device
        pool state (index, health state, consecutive failures, current
        probation backoff) and the current knob values (the config a
        controller may be retuning live)."""
        snap = self.metrics.health()
        with self._pool_lock:
            snap["devices"] = [
                {"index": s.index, "state": s.state,
                 "consecutive_failures": s.failures,
                 "backoff_s": s.backoff} for s in self._slots]
        snap["config"] = self.config.snapshot()
        return snap

    def _fail_requests(self, reqs, exc: BaseException) -> None:
        """Resolve ``reqs``' futures with ``exc`` (skipping any already
        resolved) and record the failures. Never called under the queue
        lock."""
        done = time.monotonic()
        for req in reqs:
            if req.future.done():
                continue
            self.metrics.record_request_done(done - req.enqueued_at,
                                             failed=True,
                                             priority=req.priority)
            req.future.set_exception(exc)
            if req.trace is not None:
                # failure paths settle the WHOLE trace: any open stage
                # span and the request root close with error status
                req.trace.close("error", type(exc).__name__)

    def _fail_all_pending(self, exc: BaseException) -> None:
        """Pop EVERYTHING still queued and fail it with ``exc`` — the
        supervisor's give-up path and close()'s final sweep."""
        with self._cv:
            dropped: List[_Request] = []
            for shard in self._shards.values():
                for lane in (shard.high, shard.normal):
                    dropped.extend(req for _, _, req in lane)
                    lane.clear()
            self._pending = 0
            self._high_pending = 0
            self._cv.notify_all()
        self._fail_requests(dropped, exc)

    # -- submission --------------------------------------------------------
    def submit(self, signature: PlanSignature, values,
               kind: str = "backward",
               scaling: Scaling = Scaling.NONE,
               timeout: Optional[float] = None,
               priority: str = "normal",
               trace_ctx=None) -> Future:
        """Queue one transform request; returns its Future.

        ``trace_ctx`` is an optional propagated ``obs.TraceContext``
        (a pod frontend's submit span): when given and tracing is on,
        this request is traced unconditionally — sampling already
        happened on the frontend — with the remote span as the root's
        parent, so one trace id spans the host boundary.

        ``kind`` is ``"backward"`` (values -> space) or ``"forward"``
        (space -> values, with ``scaling``). ``timeout`` (seconds) sets
        a deadline: requests still queued when it elapses fail with
        ``DeadlineExpiredError`` instead of executing, and queued
        requests are served earliest-deadline-first within their lane.
        ``priority`` is ``"normal"`` or ``"high"`` — high-lane requests
        are served before any normal-lane work and preempt a forming
        normal bucket's batching window. Raises ``QueueFullError``
        when the bounded queue is at capacity with LIVE requests
        (already-expired deadlined requests are reaped first and fail
        with ``DeadlineExpiredError``, so dead work never causes
        backpressure) and ``InvalidParameterError`` for signatures the
        registry does not hold."""
        if kind not in ("backward", "forward"):
            raise InvalidParameterError(
                f"kind must be 'backward' or 'forward', got {kind!r}")
        if priority not in _PRIORITIES:
            raise InvalidParameterError(
                f"priority must be 'normal' or 'high', got {priority!r}")
        scaling = Scaling(scaling)
        plan = self.registry.get(signature)
        if plan is None:
            raise InvalidParameterError(
                f"signature not in registry (warm up first): {signature}")
        if not isinstance(plan, TransformPlan):
            # Reject at the door, typed — the pool/batching/staging
            # machinery is built around LOCAL plans (one device per
            # request); a distributed plan spans its own mesh and pins
            # its own placement, so routing it through the device pool
            # was an undefined path that failed deep inside dispatch.
            # serve.cluster.PodFrontend is the submit surface that DOES
            # carry distributed plans (its pod-wide SPMD lane).
            raise DistributedPlanUnsupportedError(
                f"ServeExecutor serves local TransformPlans only; "
                f"signature {signature} resolves to a "
                f"{type(plan).__name__}. Submit distributed plans "
                f"through serve.cluster.PodFrontend (SPMD lane) or run "
                f"them directly (plan.backward/forward).")
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        key = (signature, kind, scaling)
        req = _Request(key, plan, kind, values, scaling, deadline,
                       priority, next(self._seq))
        # request tracing: off -> one boolean read; on -> the sampled
        # fraction of requests get a RequestTrace whose queue_wait span
        # MUST begin before the request becomes visible to the
        # dispatcher (which finishes it when the request is popped)
        rt = None
        if _obs.active() and (trace_ctx is not None
                              or _obs.GLOBAL_TRACER.sample()):
            rt = _obs.RequestTrace(
                _obs.GLOBAL_TRACER, priority, ctx=trace_ctx,
                args={"kind": kind, "scaling": scaling.value})
            rt.begin("serve.submit")
            req.trace = rt
        entry = (deadline if deadline is not None else math.inf,
                 req.seq, req)
        purged: List[_Request] = []
        if rt is not None:
            rt.finish("serve.submit")
            rt.begin("serve.queue_wait")
        try:
            with self._cv:
                if self._closed:
                    raise ServeError("executor is closed")
                if self._failed:
                    raise ServeError(
                        "executor dispatch loop has failed (crashed past "
                        "its restart budget)")
                if self._pending >= self._max_queue:
                    purged = self._purge_expired_locked(time.monotonic())
                if self._pending >= self._max_queue:
                    full = True
                else:
                    full = False
                    shard = self._shards.get(key)
                    if shard is None:
                        shard = self._shards[key] = _Shard(key, plan)
                    lane = shard.high if priority == "high" \
                        else shard.normal
                    heapq.heappush(lane, entry)
                    self._pending += 1
                    if priority == "high":
                        self._high_pending += 1
                    depth = self._pending
                    self._cv.notify_all()
        except ServeError as exc:
            if rt is not None:
                rt.close("error", type(exc).__name__)
            raise
        # future resolution + metric recording outside the queue lock
        for dead in purged:
            self.metrics.record_deadline_expired(purged=True)
            if not dead.future.done():
                dead.future.set_exception(DeadlineExpiredError(
                    "deadline expired in queue (reaped by the "
                    "backpressure sweep before dispatch)"))
            if dead.trace is not None:
                dead.trace.close("error", "DeadlineExpiredError")
        if full:
            self.metrics.record_reject_queue_full()
            if rt is not None:
                rt.close("error", "QueueFullError")
            raise QueueFullError(
                f"serving queue full ({self._max_queue} requests) — "
                f"backpressure: retry later or raise max_queue")
        self.metrics.record_enqueue(depth)
        return req.future

    def submit_backward(self, signature, values,
                        timeout: Optional[float] = None,
                        priority: str = "normal") -> Future:
        return self.submit(signature, values, "backward", timeout=timeout,
                           priority=priority)

    def submit_forward(self, signature, space,
                       scaling: Scaling = Scaling.NONE,
                       timeout: Optional[float] = None,
                       priority: str = "normal") -> Future:
        return self.submit(signature, space, "forward", scaling=scaling,
                           timeout=timeout, priority=priority)

    # -- scheduling (caller holds the lock) --------------------------------
    # lock: holds(_cv)
    def _purge_expired_locked(self, now: float) -> List[_Request]:
        """Reap queued requests whose deadline has already passed
        (caller holds the lock; futures resolve OUTSIDE it). Runs only
        on the backpressure path, so ``QueueFullError`` is never raised
        while the queue is stuffed with dead requests that dispatch
        would discard anyway. O(queue), but the full-queue path is
        already the slow path."""
        reaped: List[_Request] = []
        for shard in self._shards.values():
            for lane in (shard.high, shard.normal):
                expired = [e for e in lane if e[0] <= now]
                if not expired:
                    continue
                reaped.extend(e[2] for e in expired)
                lane[:] = [e for e in lane if e[0] > now]
                heapq.heapify(lane)
        if reaped:
            self._pending -= len(reaped)
            self._high_pending -= sum(1 for r in reaped
                                      if r.priority == "high")
        return reaped

    # lock: holds(_cv)
    def _select_shard(self) -> Optional[_Shard]:
        """The shard whose head request is most urgent: high lane before
        normal, then earliest deadline, then arrival order. O(#active
        signatures), not O(queued requests)."""
        best = best_rank = None
        for shard in self._shards.values():
            rank = shard.head_rank()
            if rank is not None and (best_rank is None
                                     or rank < best_rank):
                best, best_rank = shard, rank
        return best

    # lock: holds(_cv)
    def _pop_into(self, shard: _Shard, bucket: List[_Request],
                  limit: int) -> None:
        """Move up to ``limit - len(bucket)`` requests from the shard's
        lanes into ``bucket`` — high lane drained first, EDF order
        within each lane."""
        for lane in (shard.high, shard.normal):
            while lane and len(bucket) < limit:
                _, _, req = heapq.heappop(lane)
                bucket.append(req)
                self._pending -= 1
                if req.priority == "high":
                    self._high_pending -= 1
                if req.trace is not None:
                    req.trace.finish("serve.queue_wait")

    # lock: holds(_cv)
    def _earliest_deadline(self) -> float:
        """The soonest deadline among ALL queued requests (inf when
        none) — lane heads are heap minima, so this is O(#shards)."""
        d = math.inf
        for shard in self._shards.values():
            for lane in (shard.high, shard.normal):
                if lane and lane[0][0] < d:
                    d = lane[0][0]
        return d

    # -- dispatch ----------------------------------------------------------
    def _fill_bucket(self, shard: _Shard, bucket: List[_Request]) -> None:
        """Wait out the batching window, absorbing same-key arrivals
        into ``bucket`` until it is full or the window closes. The
        window closes EARLY when a high-priority request lands for
        another signature or a queued deadline is about to expire —
        bucket formation never holds urgent work hostage."""
        until = time.monotonic() + self._batch_window
        while len(bucket) < self._max_batch:
            with self._cv:
                self._pop_into(shard, bucket, self._max_batch)
                if len(bucket) >= self._max_batch or self._closed:
                    return
                if self._high_pending:
                    return  # high work for another key: close early
                now = time.monotonic()
                wait = until - now
                d = self._earliest_deadline()
                if d - now < wait:
                    wait = d - now  # EDF: serve it before it expires
                if wait <= 0:
                    return
                self._cv.wait(wait)

    def _pipeline_slots(self) -> int:
        """In-flight bucket window for the dispatch loop. On an
        ACCELERATOR backend it is one slot deeper than the device pool:
        pool-size buckets overlap across devices, and the extra slot
        double-buffers the host side — the dispatcher stacks and
        dispatches bucket N+1 while the device still executes bucket N.
        On the CPU backend the extra slot is a measured LOSS (two
        buckets then compute concurrently in XLA:CPU's shared intra-op
        thread pool and thrash it — the round-6 finding that serialised
        the pool in the first place; re-measured this round at -15% on
        the same-signature trace), so CPU keeps the strict
        dispatch-then-resolve window of pool size. The
        ``pipeline_depth`` knob (nonzero) overrides the choice — read
        per dispatch iteration, so a controller retune applies live."""
        depth = self._pipeline_depth
        if depth is not None:
            return depth
        if self._auto_extra is None:
            import jax
            self._auto_extra = 0 if jax.default_backend() == "cpu" else 1
        return len(self._devices) + self._auto_extra

    def _run_dispatcher(self) -> None:
        """Crash-proof supervisor around :meth:`_dispatch_loop`. An
        exception escaping the loop's per-bucket error handling fails
        the crashing bucket's futures with ``ExecutorCrashedError``,
        flushes in-flight buckets (resolving them normally when their
        results are intact), and restarts the loop — up to
        ``max_dispatch_restarts`` times. Past the budget it fails
        everything queued and marks the executor failed: a dispatch
        crash may degrade the service, it can NEVER silently strand a
        caller on an unresolved future."""
        while True:
            try:
                self._dispatch_loop()
                return  # clean shutdown via close()
            except Exception as exc:
                self.metrics.record_dispatcher_crash()
                if _obs.active():
                    _obs.GLOBAL_TRACER.instant(
                        "serve.dispatcher_crash",
                        args={"error": repr(exc)[:200]})
                crash = ExecutorCrashedError(
                    f"dispatch loop crashed: {exc!r}")
                forming, self._forming = self._forming, None
                self._fail_requests(forming or [], crash)
                while self._inflight:
                    work = self._inflight.popleft()
                    try:
                        self._finish(*work)
                    except Exception:
                        self._fail_requests(work[0], crash)
                with self._cv:
                    self._restarts += 1
                    give_up = self._restarts > self._max_restarts
                    if give_up:
                        self._failed = True
                if not give_up:
                    self.metrics.record_dispatcher_restart()
                    if _obs.active():
                        _obs.GLOBAL_TRACER.instant(
                            "serve.dispatcher_restart")
                    self._push_health()
                    continue
                self._fail_all_pending(crash)
                self._push_health()
                return

    def _dispatch_loop(self) -> None:
        # Bounded in-flight pipelining (see _pipeline_slots): futures
        # resolve in _finish, after materialisation. In-flight work and
        # the forming bucket live on the executor (not loop locals) so
        # the supervisor can resolve their futures after a crash.
        inflight = self._inflight
        while True:
            # read the (hot-swappable) depth each iteration so a
            # controller retune of pipeline_depth applies immediately
            depth = self._pipeline_slots()
            self._check_fault("loop")
            shard = bucket = None
            with self._cv:
                if self._pending:
                    shard = self._select_shard()
                    bucket = []
                    self._pop_into(shard, bucket, self._max_batch)
                    depth_now = self._pending
                elif inflight:
                    pass  # fall through: flush one in-flight bucket
                elif self._closed:
                    return
                else:
                    self._cv.wait()
                    continue
            if bucket is None:
                # peek-then-pop: a crash inside _finish leaves the
                # bucket reachable for the supervisor's flush
                self._finish(*inflight[0])
                inflight.popleft()
                continue
            self._forming = bucket
            self.metrics.record_dequeue(depth_now)
            bt = self._bucket_trace(bucket)
            if bt is not None:
                bt.begin("serve.bucket_formation")
            # Wait out the batching window only on a TRICKLE (nothing
            # else queued after the take): under backlog the queued
            # requests are already late and a window wait just adds
            # latency without improving fill — the take itself drains
            # every same-key request the shard holds. The window wait
            # runs INSIDE the bucket trace's protective try: a crash
            # anywhere between formation-begin and execute must close
            # the bucket spans (the supervisor settles request traces,
            # not bucket traces — the static span-closure pass found
            # this window).
            try:
                # lock: waived(benign racy pre-check - _fill_bucket re-reads _closed under the cv before waiting)
                if len(bucket) < self._max_batch and depth_now == 0 \
                        and self._batching and self._batch_window > 0 \
                        and not self._closed:
                    self._fill_bucket(shard, bucket)
                work = self._execute(shard, bucket, bt)
            except BaseException:
                if bt is not None:
                    bt.end_all("error", "ExecutorCrashedError")
                raise
            if work is not None:
                inflight.append(work)
            self._forming = None
            while len(inflight) >= depth:
                self._finish(*inflight[0])
                inflight.popleft()

    def _drain_once(self) -> None:
        """Synchronous drain (close() on a never-started executor, and
        the bench CLI's deterministic ``--smoke`` waves): buckets form
        from whatever is queued, no windows, no pipelining."""
        while True:
            with self._cv:
                if not self._pending:
                    return
                shard = self._select_shard()
                bucket: List[_Request] = []
                self._pop_into(shard, bucket, self._max_batch)
                depth_now = self._pending
            self.metrics.record_dequeue(depth_now)
            bt = self._bucket_trace(bucket)
            if bt is not None:
                # span: closed-by(ServeExecutor._execute)
                bt.begin("serve.bucket_formation")
            work = self._execute(shard, bucket, bt)
            if work is not None:
                self._finish(*work)

    # -- device pool health ------------------------------------------------
    def _acquire_slot(self) -> _DeviceSlot:
        """Next servable pool slot, round-robin, skipping quarantined
        devices. A quarantined device whose backoff has elapsed is
        flipped to probation and RETURNED — the caller's request is the
        canary that decides readmission. Raises
        ``NoHealthyDeviceError`` when every slot is quarantined and
        none is due."""
        probed = None
        with self._pool_lock:
            now = time.monotonic()
            n = len(self._slots)
            for _ in range(n):
                slot = self._slots[self._rotor % n]
                self._rotor += 1
                if slot.state == "healthy":
                    return slot
                if slot.state == "quarantined" and now >= slot.until:
                    slot.state = "probation"
                    probed = slot
                    break
                # quarantined-and-not-due, or probation with a canary
                # already outstanding: skip
        if probed is not None:
            self.metrics.record_probation()
            _obs.record_event("device.probation", device=probed.index,
                              backoff_s=probed.backoff)
            if _obs.active():
                _obs.GLOBAL_TRACER.instant(
                    "serve.probation", track=_dev_track(probed),
                    args={"backoff_s": probed.backoff})
            return probed
        # lock: waived(pool list is append-never after __init__ - diagnostic count only)
        raise NoHealthyDeviceError(
            f"all {len(self._slots)} pool devices are quarantined and "
            f"none is due for probation")

    def _device_ok(self, slot: Optional[_DeviceSlot]) -> None:
        """A request completed on ``slot``: reset its failure streak; a
        probation canary's success re-admits the device."""
        if slot is None:
            return
        readmitted = False
        with self._pool_lock:
            slot.failures = 0
            if slot.state == "probation":
                slot.state = "healthy"
                slot.backoff = self._q_backoff
                readmitted = True
        if readmitted:
            self.metrics.record_readmission()
            _obs.record_event("device.readmit", device=slot.index)
            if _obs.active():
                _obs.GLOBAL_TRACER.instant("serve.readmission",
                                           track=_dev_track(slot))
            self._push_health()

    def _device_fail(self, slot: Optional[_DeviceSlot],
                     exc: Optional[BaseException] = None) -> None:
        """A request failed on ``slot``: bump its consecutive-failure
        count; crossing ``quarantine_after`` (or failing its probation
        canary) quarantines it with exponential backoff.

        ``exc`` drives the ATTRIBUTION gate (the round-11 fix): a
        REQUEST-attributed failure (``faults.attributes_device`` False
        — a poisoned payload fails the same way on every healthy
        device) never charges the device's streak, so a pure
        poisoned-request flood can no longer spuriously quarantine a
        healthy device. A probation canary that failed for request
        reasons returns the slot to quarantine with its verdict
        undecided — immediately probe-able, backoff NOT doubled."""
        if slot is None or self._q_after <= 0:
            return
        if exc is not None and not attributes_device(exc):
            self.metrics.record_request_attributed_failure()
            with self._pool_lock:
                if slot.state == "probation":
                    slot.state = "quarantined"
                    slot.until = time.monotonic()
            return
        quarantined = False
        with self._pool_lock:
            slot.failures += 1
            if slot.state == "probation":
                slot.backoff = min(slot.backoff * 2.0,
                                   QUARANTINE_BACKOFF_CAP)
                quarantined = True
            elif slot.failures >= self._q_after:
                quarantined = True
            if quarantined:
                slot.state = "quarantined"
                slot.until = time.monotonic() + slot.backoff
                slot.failures = 0
        if quarantined:
            self.metrics.record_quarantine()
            _obs.record_event("device.quarantine", device=slot.index,
                              backoff_s=slot.backoff)
            if _obs.active():
                _obs.GLOBAL_TRACER.instant(
                    "serve.quarantine", track=_dev_track(slot),
                    args={"backoff_s": slot.backoff})
            self._push_health()

    # -- execution ---------------------------------------------------------
    def prewarm(self, signature: PlanSignature,
                scaling: Scaling = Scaling.NONE,
                batch_sizes=()) -> None:
        """Compile/warm every executable this executor can dispatch for
        ``signature``: the serial backward/forward pair plus each fused
        batch shape of the planned-batch ladder — plus any
        ``batch_sizes`` a caller expects to PIN (exact shapes the
        adaptive observer would otherwise compile on first pinned
        dispatch) — on EVERY pool device (jit caches one executable per
        device). Call once per signature before traffic — on TPU this is
        where the persistent compilation cache pays out; without it the
        first bucket per (shape, device, ladder size) eats a compile
        inside a request's latency."""
        plan = self.registry.get(signature)
        if plan is None:
            raise InvalidParameterError(
                f"signature not in registry: {signature}")
        # prewarm is a blocking pre-traffic step: join the background
        # table build so a dead builder surfaces here, typed, instead
        # of poisoning the first request routed at this signature
        plan.check_build(wait=True)
        import jax
        t_warm = time.perf_counter()
        nv = plan.index_plan.num_values
        zeros = (np.zeros((nv, 2), np.float32)
                 if plan.precision == "single"
                 else np.zeros(nv, np.complex128))
        ladder = sorted({self._padded_size(b)
                         for b in range(2, self._max_batch + 1)}
                        | {int(b) for b in batch_sizes if int(b) >= 2})
        for device in self._devices:
            space = plan.backward(zeros, device=device)
            out = [plan.forward(space, scaling, device=device)]
            if self._batching:
                for size in ladder:
                    if not fusion_eligible(plan, size):
                        continue
                    out.append(plan.backward_batched(
                        [zeros] * size, device=device))
                    out.append(plan.forward_batched(
                        [space] * size, scaling, device=device))
            jax.block_until_ready(out)
        # compile observability: the batch-ladder compiles happen here
        # on a warm server (first-dispatch compiles happen inside the
        # serve.dispatch span otherwise)
        _obs.record_compile("prewarm", time.perf_counter() - t_warm,
                            t_warm, ladder=len(ladder),
                            devices=len(self._devices),
                            num_values=nv)

    def _padded_size(self, b: int) -> int:
        """The fallback batch ladder (``multi.planned_batch_size``):
        smallest power of two >= ``b``, capped at ``max_batch``."""
        return planned_batch_size(b, self._max_batch)

    def _prewarm_pin_async(self, shard: _Shard, b: int) -> None:
        """ROADMAP prewarm-on-pin: the observer's streak is ONE bucket
        short of pinning exact shape ``b`` — compile that batched
        executable on a background thread now, so the first pinned
        dispatch hits a warm jit cache (jit caches are shared across
        threads) instead of eating the compile blip inside a request.
        Best-effort: a failed prewarm just means the compile happens at
        dispatch, exactly as before."""
        key = (shard.key, b)
        if key in self._prewarm_threads \
                or not fusion_eligible(shard.plan, b):
            return
        template = self._row_template(shard)
        if template is None:
            return  # device-staged plans: no host zero-batch to trace
        plan, kind, scaling = shard.plan, shard.key[1], shard.key[2]
        row_shape, dtype = template
        devices = list(self._devices)
        metrics = self.metrics

        def compile_shape():
            try:
                import jax
                t_pin = time.perf_counter()
                zeros = np.zeros((b,) + row_shape, dtype)
                for device in devices:
                    if kind == "backward":
                        out = plan.backward_batched(zeros, device=device)
                    else:
                        out = plan.forward_batched(zeros, scaling,
                                                   device=device)
                    jax.block_until_ready(out)
                metrics.record_pin_prewarm()
                _obs.record_compile("pin_prewarm",
                                    time.perf_counter() - t_pin, t_pin,
                                    batch=b, kind=kind)
            except Exception:
                pass

        thread = threading.Thread(target=compile_shape, daemon=True,
                                  name="spfft-serve-pin-prewarm")
        self._prewarm_threads[key] = thread
        thread.start()

    def _dispatch_shape(self, shard: _Shard, b: int) -> Tuple[int, bool]:
        """The batch shape a fused bucket of ``b`` live rows dispatches
        at, and whether that shape is exact (pinned or ladder-exact).

        The observer pins ``b`` once it repeats ``pin_after`` times
        consecutively; pinned shapes live in a per-signature LRU capped
        at ``max_pinned_shapes``. One repeat BEFORE the pin lands the
        exact-shape compile starts on a background thread
        (prewarm-on-pin). Churny traffic (no streak) falls back to the
        pow2 ladder, so the compiled-shape count stays bounded either
        way. Dispatcher thread only — no lock."""
        ladder = self._padded_size(b)
        if ladder == b:
            # ladder already exact: zero pad rows for free, no pin
            # needed (and none counted — pinned_batches reads the
            # adaptive path only)
            return b, False
        if self._pin_after <= 0:
            return ladder, False
        if b == shard.last_size:
            shard.streak += 1
        else:
            shard.last_size = b
            shard.streak = 1
        pins = self._pins.get(shard.key[0])
        if pins is not None and b in pins:
            pins.move_to_end(b)
            return b, True
        if self._prewarm_on_pin and self._pin_after >= 2 \
                and shard.streak == self._pin_after - 1:
            self._prewarm_pin_async(shard, b)
        if shard.streak >= self._pin_after:
            if pins is None:
                pins = self._pins[shard.key[0]] = collections.OrderedDict()
            pins[b] = None
            while len(pins) > self._max_pinned:
                pins.popitem(last=False)
            return b, True
        return ladder, False

    # -- staging -----------------------------------------------------------
    def _row_template(self, shard: _Shard):
        if not shard.template_ready:
            shard.row_template = shard.plan.batch_row_template(
                "values" if shard.key[1] == "backward" else "space")
            shard.template_ready = True
        return shard.row_template

    def _stage(self, shard: _Shard, live: List[_Request], shape: int):
        """Stack ``live`` payloads (plus pad rows up to ``shape``) into
        a reusable preallocated host buffer when every payload coerces
        to a host row of the plan's template — one allocation per
        (shard, shape) steady-state, one device transfer per bucket.
        Returns ``(batch_arg, buffer)``; ``buffer`` is None on the
        fallback list path (device-array payloads, double-single plans),
        where the plan's own ``_stack_coerced`` handles staging.

        Buffers come from a free-list and are returned in
        :meth:`_finish` AFTER the bucket materialises — ``jnp.asarray``
        may alias host memory on the CPU backend, so a buffer is never
        rewritten while its bucket may still read it."""
        template = self._row_template(shard)
        if template is not None:
            plan, kind = shard.plan, shard.key[1]
            coerce = (plan._coerce_values if kind == "backward"
                      else plan._coerce_space)
            rows = [coerce(req.values) for req in live]
            row_shape, dtype = template
            if all(isinstance(r, np.ndarray) and r.shape == row_shape
                   and r.dtype == dtype for r in rows):
                pool_key = (shard.key, shape)
                free = self._staging.get(pool_key)
                buf = free.pop() if free else np.empty(
                    (shape,) + row_shape, dtype)
                for i, r in enumerate(rows):
                    buf[i] = r
                for j in range(len(rows), shape):
                    buf[j] = buf[0]  # pad rows repeat row 0
                return buf, buf
        values = [req.values for req in live]
        values += [values[0]] * (shape - len(values))
        return values, None

    def _release(self, shard_key, shape: int,
                 buf: Optional[np.ndarray]) -> None:
        if buf is not None:
            self._staging.setdefault((shard_key, shape), []).append(buf)

    def _run_one(self, req: _Request, pooled: bool):
        """One SYNCHRONOUS serial execution of a single request —
        dispatch plus materialisation — used by recovery and retry.
        Updates the device health accounting; raises on failure
        (``NoHealthyDeviceError`` propagates before any device is
        charged)."""
        import jax
        slot = self._acquire_slot() if pooled else None
        device = slot.device if slot is not None else None
        try:
            self._check_fault("dispatch",
                              slot.index if slot is not None else None)
            if req.kind == "backward":
                res = req.plan.backward(req.values, device=device)
            else:
                res = req.plan.forward(req.values, req.scaling,
                                       device=device)
            jax.block_until_ready(res)
        except Exception as exc:
            self._device_fail(slot, exc)
            raise
        self._device_ok(slot)
        return res

    def _resolve_one(self, req: _Request, res) -> None:
        if req.future.done():
            return
        done = time.monotonic()
        self.metrics.record_request_done(done - req.enqueued_at,
                                         priority=req.priority)
        rt = req.trace
        if rt is not None:
            rt.begin("serve.resolve")
        req.future.set_result(res)
        if rt is not None:
            rt.finish("serve.resolve")
            rt.close()

    def _annotate_fallback(self, live, cause: BaseException) -> None:
        """Bucket-fallback annotation on every traced member (the ISSUE
        contract: retry/fallback/quarantine events attach to spans)."""
        if not _obs.active():
            return
        for req in live:
            if req.trace is not None:
                req.trace.annotate("serve.bucket_fallback",
                                   error=repr(cause)[:200])

    def _recover_serial(self, live: List[_Request], cause: BaseException,
                        pooled: bool) -> None:
        """Bucket-failure isolation: the fused bucket raised ``cause``,
        so re-execute every live request SERIALLY — only genuinely
        poisoned requests fail; healthy co-batched requests still return
        their (bit-exact) results. The serial re-executions draw on each
        request's PER-PRIORITY retry budget (``retry_budget``; high-lane
        requests get more attempts than normal ones): a transient
        failure that persists through the budget becomes
        ``RetryExhaustedError`` (carrying the cause), a permanent one
        surfaces as itself."""
        for req in live:
            budget = max(1, self._retry_budget[req.priority])
            for attempt in range(budget):
                self.metrics.record_retry(req.priority)
                if req.trace is not None:
                    req.trace.annotate("serve.retry",
                                       attempt=attempt + 1,
                                       budget=budget)
                try:
                    res = self._run_one(req, pooled)
                except NoHealthyDeviceError as exc:
                    self.metrics.record_no_healthy_device()
                    self._fail_requests([req], exc)
                    break
                except Exception as exc:
                    if attempt + 1 < budget and is_transient(exc):
                        continue
                    if is_transient(exc):
                        self.metrics.record_retry_exhausted(req.priority)
                        self._fail_requests([req], RetryExhaustedError(
                            f"request failed its fused-bucket fallback "
                            f"({attempt + 1}/{budget} "
                            f"{req.priority}-class attempts; bucket "
                            f"error: {cause!r})", cause=exc))
                    else:
                        self._fail_requests([req], exc)
                    break
                else:
                    self._resolve_one(req, res)
                    break

    def _retry_request(self, req: _Request, first_exc: BaseException,
                       pooled: bool) -> None:
        """A serial execution of ``req`` failed with ``first_exc``:
        permanent failures surface immediately; transient ones get the
        request's PER-PRIORITY bounded retry budget, failing with
        ``RetryExhaustedError`` once it is spent."""
        budget = self._retry_budget[req.priority]
        if not is_transient(first_exc) or budget < 1:
            self._fail_requests([req], first_exc)
            return
        for attempt in range(budget):
            self.metrics.record_retry(req.priority)
            if req.trace is not None:
                req.trace.annotate("serve.retry", attempt=attempt + 1,
                                   budget=budget)
            try:
                res = self._run_one(req, pooled)
            except NoHealthyDeviceError as exc:
                self.metrics.record_no_healthy_device()
                self._fail_requests([req], exc)
                return
            except Exception as exc:
                if attempt + 1 < budget and is_transient(exc):
                    continue
                self.metrics.record_retry_exhausted(req.priority)
                self._fail_requests([req], RetryExhaustedError(
                    f"transient failure persisted through "
                    f"{attempt + 1}/{budget} {req.priority}-class "
                    f"retries (first error: {first_exc!r})", cause=exc))
                return
            self._resolve_one(req, res)
            return

    def _bucket_trace(self, bucket) -> Optional[_BucketTrace]:
        """A :class:`_BucketTrace` when tracing is on and any member
        request was sampled; None otherwise (one boolean read on the
        disabled path)."""
        if not _obs.active():
            return None
        traced = [r for r in bucket if r.trace is not None]
        if not traced:
            return None
        return _BucketTrace(_obs.GLOBAL_TRACER, traced)

    def _execute(self, shard: _Shard, bucket: List[_Request],
                 bt: Optional[_BucketTrace] = None):
        """Deadline-check and DISPATCH one bucket. Returns ``(live,
        results, shard_key, shape, buf, slots, fused, bt)`` with
        results possibly still executing (the dispatch loop pipelines
        them), or ``None`` when nothing survived the deadline check or
        every request resolved on a failure path. ``bt`` carries the
        bucket-level trace spans; its ``serve.device_execute`` span
        stays open across the return and closes in :meth:`_finish`."""
        now = time.monotonic()
        # control-plane signal: enqueue->dispatch wait per request
        # (includes any batching window sat out) — what the feedback
        # controller weighs against device-execute time
        self.metrics.record_queue_waits(
            [now - req.enqueued_at for req in bucket])
        live: List[_Request] = []
        expired: List[_Request] = []
        for req in bucket:
            (expired if req.deadline is not None and now > req.deadline
             else live).append(req)
        for req in expired:
            self.metrics.record_deadline_expired()
            req.future.set_exception(DeadlineExpiredError(
                f"deadline expired after "
                f"{now - req.enqueued_at:.3f}s in queue"))
            if req.trace is not None:
                req.trace.close("error", "DeadlineExpiredError")
        if not live:
            if bt is not None:
                bt.end_all()
            return None
        if bt is not None:
            bt.end("serve.bucket_formation")
        plan = live[0].plan
        kind = live[0].kind
        scaling = live[0].scaling
        # device pools apply to LOCAL plans only — a distributed plan
        # already spans its mesh and pins its own placement
        pooled = (self._devices != [None]
                  and isinstance(plan, TransformPlan))
        b = len(live)
        shape, exact = b, False
        fused = False
        if self._batching and b >= 2:
            shape, exact = self._dispatch_shape(shard, b)
            fused = fusion_eligible(plan, shape)
        buf = None
        slot: Optional[_DeviceSlot] = None
        t0 = time.perf_counter()
        if fused:
            if bt is not None:
                bt.begin("serve.stage", args={"batch": b, "shape": shape})
            try:
                # Planned-batch execution (the cuFFT idiom): dispatch at
                # the exact pinned shape when the observer has locked
                # on, else pad up to the next pow2 ladder size so only
                # O(log max_batch) batched executables ever compile per
                # plan. vmap rows are independent, so pad rows (repeats
                # of row 0) cannot perturb the live rows and results
                # stay bit-identical to serial execution. The whole
                # bucket lands on ONE pool device; successive buckets
                # rotate.
                self._check_fault("stage")
                batch_arg, buf = self._stage(shard, live, shape)
                slot = self._acquire_slot() if pooled else None
                device = slot.device if slot is not None else None
                if bt is not None:
                    bt.end("serve.stage")
                    bt.begin("serve.dispatch", track=_dev_track(slot))
                self._check_fault(
                    "dispatch", slot.index if slot is not None else None)
                t1 = time.perf_counter()
                if kind == "backward":
                    stacked = plan.backward_batched(batch_arg,
                                                    device=device)
                else:
                    stacked = plan.forward_batched(batch_arg, scaling,
                                                   device=device)
                results = [stacked[i] for i in range(b)]
            except NoHealthyDeviceError as exc:
                if bt is not None:
                    bt.end_all("error", type(exc).__name__)
                self._release(shard.key, shape, buf)
                self.metrics.record_no_healthy_device()
                self._fail_requests(live, exc)
                return None
            except Exception as exc:
                # bucket-failure isolation: never fail the whole bucket
                # for one poisoned request — fall back to per-request
                # serial re-execution
                if bt is not None:
                    bt.end_all("error", type(exc).__name__)
                self._release(shard.key, shape, buf)
                self._device_fail(slot, exc)
                self.metrics.record_bucket_fallback()
                self._annotate_fallback(live, exc)
                self._recover_serial(live, exc, pooled)
                return None
            t2 = time.perf_counter()
            self.metrics.record_batch(b, True, padded_rows=shape - b,
                                      pinned=exact,
                                      stage_s=t1 - t0, dispatch_s=t2 - t1)
            if bt is not None:
                bt.end("serve.dispatch")
                bt.begin("serve.device_execute", track=_dev_track(slot))
            return (live, results, shard.key, shape, buf, [slot], True,
                    bt, t1)
        # serial path: dispatch every request before blocking on any
        # result (the multi.py async-overlap idiom), fanned round-robin
        # across the device pool; failures are isolated per request
        shape, exact = b, False
        keep: List[_Request] = []
        results = []
        slots: List[Optional[_DeviceSlot]] = []
        if bt is not None:
            bt.begin("serve.dispatch", args={"batch": b, "serial": True})
        for req in live:
            slot = None
            try:
                slot = self._acquire_slot() if pooled else None
                device = slot.device if slot is not None else None
                self._check_fault(
                    "dispatch", slot.index if slot is not None else None)
                if kind == "backward":
                    res = plan.backward(req.values, device=device)
                else:
                    res = plan.forward(req.values, scaling, device=device)
            except NoHealthyDeviceError as exc:
                self.metrics.record_no_healthy_device()
                self._fail_requests([req], exc)
                continue
            except Exception as exc:
                self._device_fail(slot, exc)
                self._retry_request(req, exc, pooled)
                continue
            keep.append(req)
            results.append(res)
            slots.append(slot)
        t2 = time.perf_counter()
        self.metrics.record_batch(b, False, dispatch_s=t2 - t0)
        if bt is not None:
            bt.end("serve.dispatch")
        if not keep:
            if bt is not None:
                bt.end_all()
            return None
        if bt is not None:
            bt.begin("serve.device_execute",
                     track=_dev_track(slots[0] if slots else None))
        return (keep, results, shard.key, shape, buf, slots, False, bt,
                t0)

    def _materialise(self, results) -> None:
        """``block_until_ready`` on a bucket's results, under the
        ``execute_timeout_ms`` watchdog when that knob is non-zero. The
        wait runs on a short-lived daemon worker; if it outlives the
        deadline the worker is abandoned (a wedged XLA execute cannot
        be cancelled from the host) and the bucket fails with the TYPED
        transient :class:`ExecuteTimeoutError`, which feeds the
        existing retry + quarantine ladder exactly like a device fault
        — closing the last "zero hangs" gap. With the knob at 0
        (default) this is the plain inline wait round 8 shipped."""
        import jax
        timeout_ms = self.config.execute_timeout_ms
        if timeout_ms <= 0:
            self._check_fault("materialise")
            jax.block_until_ready(results)
            return
        box: Dict[str, BaseException] = {}
        done = threading.Event()

        def _work():
            try:
                self._check_fault("materialise")
                jax.block_until_ready(results)
            except BaseException as exc:
                box["exc"] = exc
            finally:
                done.set()

        worker = threading.Thread(target=_work, daemon=True,
                                  name="spfft-materialise")
        worker.start()
        if not done.wait(timeout_ms / 1000.0):
            _obs.GLOBAL_COUNTERS.inc("spfft_execute_timeouts_total")
            raise ExecuteTimeoutError(
                f"bucket materialisation exceeded execute_timeout_ms="
                f"{timeout_ms:g} ms; abandoning the wedged execute")
        exc = box.get("exc")
        if exc is not None:
            raise exc

    def _finish(self, live, results, shard_key=None, shape=0,
                buf=None, slots=None, fused=False, bt=None,
                t_disp=None) -> None:
        """Materialise a dispatched bucket and resolve its futures:
        latency samples measure completion (not dispatch), and async XLA
        failures surface here as exceptions instead of poisoned arrays.
        A fused bucket that fails to materialise takes the same
        per-request serial recovery as a failed dispatch; a serial
        bucket isolates the failure by materialising per request. The
        staging buffer returns to its free-list only now — after
        materialisation — so reuse can never race the device
        transfer. ``bt``'s spans (the open ``serve.device_execute``
        plus the ``serve.materialise`` opened here) close before any
        member future resolves, so bucket spans always nest inside
        their request root."""
        import jax
        if bt is not None:
            bt.begin("serve.materialise",
                     track=_dev_track(slots[0] if slots else None))
        try:
            self._materialise(results)
        except Exception as exc:
            if bt is not None:
                bt.end_all("error", type(exc).__name__)
            self._release(shard_key, shape, buf)
            pooled = bool(slots) and slots[0] is not None
            if fused:
                self._device_fail(slots[0] if slots else None, exc)
                self.metrics.record_bucket_fallback()
                self._annotate_fallback(live, exc)
                self._recover_serial(live, exc, pooled)
                return
            for i, req in enumerate(live):
                slot = slots[i] if slots else None
                try:
                    jax.block_until_ready(results[i])
                except Exception as exc_i:
                    self._device_fail(slot, exc_i)
                    self._retry_request(req, exc_i, slot is not None)
                    continue
                self._device_ok(slot)
                self._resolve_one(req, results[i])
            return
        if bt is not None:
            bt.end("serve.materialise")
            bt.end("serve.device_execute")
        if t_disp is not None:
            # control-plane signal: dispatch -> materialised per bucket
            self.metrics.record_device_execute(
                time.perf_counter() - t_disp)
        self._release(shard_key, shape, buf)
        for slot in (slots or ()):
            self._device_ok(slot)
        done = time.monotonic()
        for req, res in zip(live, results):
            if req.future.done():
                continue
            self.metrics.record_request_done(done - req.enqueued_at,
                                             priority=req.priority)
            rt = req.trace
            if rt is not None:
                rt.begin("serve.resolve")
            req.future.set_result(res)
            if rt is not None:
                rt.finish("serve.resolve")
                rt.close()

    # -- introspection -----------------------------------------------------
    def pinned_shapes(self, signature: PlanSignature) -> Tuple[int, ...]:
        """The exact batch shapes currently pinned for ``signature``
        (LRU order, oldest first). Diagnostic only — reads dispatcher-
        owned state, so values are advisory under live traffic."""
        pins = self._pins.get(signature)
        return tuple(pins) if pins else ()

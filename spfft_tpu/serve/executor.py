"""Concurrent batching executor: futures in, fused batches out.

The reference's throughput lever for many independent transforms is its
multi-transform scheduler — hand-interleaved phases of N transforms
(reference: src/spfft/multi_transform_internal.hpp:47-145), reproduced
here as ``spfft_tpu.multi``. This module turns that primitive into a
request-driven serving layer: callers ``submit(signature, values)`` from
any number of threads and get ``concurrent.futures.Future``s back; a
single dispatcher thread buckets same-signature requests and executes
full buckets through the plan's fused batched executables (the
``multi.py`` fused path — one vmapped dispatch for B requests),
stragglers through the ordinary serial path.

The dispatch path is built for hardware-speed serving:

* **Per-signature pending shards** — requests land in a shard keyed by
  ``(signature, kind, scaling)``; bucket formation pops one shard's
  lanes instead of re-scanning one global queue per take (the PR-1
  structure, O(queue) per bucket).
* **Priority lanes + EDF** — ``submit(..., priority="high")`` enters a
  shard's high lane, served before ANY normal-lane work; within each
  lane requests order earliest-deadline-first (deadline-less requests
  keep FIFO order behind every deadlined one). A forming normal bucket
  closes its batching window early when a high-priority request arrives
  for another signature or a queued deadline is about to expire.
* **Adaptive batch-shape pinning** — a per-shard observer watches
  fused bucket sizes; once the same size repeats ``pin_after``
  consecutive times, that EXACT shape is pinned (per-signature LRU,
  ``max_pinned_shapes`` entries) and buckets of that size dispatch with
  ZERO pad rows. Shape churn never pins and falls back to the pow2
  ladder (``multi.planned_batch_size``), keeping compile count bounded
  by O(log max_batch) + max_pinned_shapes per signature.
* **Reusable staging buffers + double-buffered pipelining** — fused
  buckets stack into preallocated per-(shard, shape) host buffers
  (checked out from a free-list, returned when the bucket resolves, so
  a buffer is never rewritten while its transfer may still alias it),
  and the in-flight window is one deeper than the device pool so the
  host stacks bucket N+1 while the devices execute bucket N. Future
  resolution and metric recording happen outside the queue lock.

Correctness contract: any interleaving of concurrent requests produces
results BIT-IDENTICAL to running each request alone on its plan. Three
structural facts make this hold: (1) requests only share a bucket when
their signatures are equal, and equal signatures resolve to the same
plan object (registry invariant); (2) the fused batched pipeline is the
vmapped form of the serial pipeline over identical static tables — vmap
rows are independent, so pad rows (repeats of row 0) and the CHOICE of
batch shape (pinned exact vs ladder) cannot perturb the live rows;
(3) staged host buffers carry exactly the per-row coerced layout
(``plan.batch_row_template``) at the plan's own dtype. Verified
bit-exact against the serial path by the tier-1 concurrency fuzz
(tests/test_serve_executor.py), which mixes priorities and pinned
shapes. The batching policy (when fusion wins) is
``multi.fusion_eligible`` — the SAME gate ``multi_transform_*`` uses,
so the serving layer degrades to serial dispatch exactly where the
library itself would.

Flow control is explicit and bounded: a fixed-capacity queue whose
overflow REJECTS with ``QueueFullError`` (backpressure the caller can
see, never silent unbounded buffering), per-request deadlines that
expire queued work with ``DeadlineExpiredError`` before it wastes device
time, and ``batching=False`` (or a fusion-ineligible regime) degrading
gracefully to serial per-request dispatch.
"""

from __future__ import annotations

import collections
import heapq
import itertools
import math
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..errors import (DeadlineExpiredError, InvalidParameterError,
                      QueueFullError, ServeError)
from ..multi import fusion_eligible, planned_batch_size
from ..plan import TransformPlan
from ..types import Scaling
from .metrics import ServeMetrics
from .registry import PlanRegistry, PlanSignature

#: Default same-signature batching window (seconds): long enough to
#: collect a burst dispatched by concurrent submitters, short enough to
#: be invisible next to a single transform execution (ms-class). Retuned
#: round 7 against measured arrival/orchestration latency: 8 submitter
#: threads spread a bucket-of-8 worth of arrivals over ~0.1 ms, so 1 ms
#: still absorbs a burst while halving the worst-case latency a trickle
#: request pays waiting for company that never arrives; throughput at
#: 1 ms vs the old 2 ms is noise-equivalent under backlog, where the
#: window never applies (BENCHMARKS.md round-7).
DEFAULT_BATCH_WINDOW = 0.001

#: Default bucket cap — the fused-batch regime gate
#: (multi.FUSED_BATCH_MAX_GRID) bounds total work; this bounds latency
#: amplification for the first request of a burst.
DEFAULT_MAX_BATCH = 8

DEFAULT_MAX_QUEUE = 256

#: Consecutive same-size fused buckets before that exact shape is
#: pinned. 3 rides out one-off stragglers without delaying a genuinely
#: stable trace; 0 disables pinning.
DEFAULT_PIN_AFTER = 3

#: Pinned exact shapes kept per signature (LRU). Each pin compiles one
#: extra executable per (kind, device), so the total compile bound stays
#: O(log max_batch) ladder + this.
DEFAULT_MAX_PINNED = 4

_PRIORITIES = ("normal", "high")


class _Request:
    __slots__ = ("key", "plan", "kind", "values", "scaling", "deadline",
                 "priority", "seq", "future", "enqueued_at")

    def __init__(self, key, plan, kind, values, scaling, deadline,
                 priority, seq):
        self.key = key
        self.plan = plan
        self.kind = kind
        self.values = values
        self.scaling = scaling
        self.deadline = deadline
        self.priority = priority
        self.seq = seq
        self.future: Future = Future()
        self.enqueued_at = time.monotonic()


class _Shard:
    """Pending work + batch-shape observer for one (signature, kind,
    scaling) key. Lanes are heaps of ``(deadline-or-inf, seq, request)``
    — EDF within the lane, FIFO among deadline-less requests. The shard
    survives idle periods so its observer state (and the signature's
    pinned shapes) persist across traffic gaps."""

    __slots__ = ("key", "plan", "high", "normal", "last_size", "streak",
                 "row_template", "template_ready")

    def __init__(self, key, plan):
        self.key = key
        self.plan = plan
        self.high: List[Tuple[float, int, _Request]] = []
        self.normal: List[Tuple[float, int, _Request]] = []
        self.last_size = 0
        self.streak = 0
        self.row_template = None
        self.template_ready = False

    def pending(self) -> bool:
        return bool(self.high or self.normal)

    def head_rank(self):
        """Scheduling rank of this shard's most urgent request:
        ``(lane, deadline-or-inf, seq)`` — high lane beats normal,
        then EDF, then arrival order. None when empty."""
        if self.high:
            return (0, self.high[0][0], self.high[0][1])
        if self.normal:
            return (1, self.normal[0][0], self.normal[0][1])
        return None


class ServeExecutor:
    """One dispatcher thread over bounded per-signature request shards.

    ``registry`` resolves signatures to plans (requests for unknown
    signatures are rejected at submit time — a server warms its shapes
    up front; see ``PlanRegistry.warmup``). Use as a context manager or
    call :meth:`close` to drain and stop.

    ``autostart=False`` defers the dispatcher thread until
    :meth:`start` — used by tests (and pre-warm scripts) to stage a
    queue deterministically before any dispatch happens.
    """

    def __init__(self, registry: PlanRegistry,
                 batch_window: float = DEFAULT_BATCH_WINDOW,
                 max_batch: int = DEFAULT_MAX_BATCH,
                 max_queue: int = DEFAULT_MAX_QUEUE,
                 batching: bool = True,
                 devices=None,
                 metrics: Optional[ServeMetrics] = None,
                 pin_after: int = DEFAULT_PIN_AFTER,
                 max_pinned_shapes: int = DEFAULT_MAX_PINNED,
                 pipeline_depth: Optional[int] = None,
                 autostart: bool = True):
        if max_batch < 1 or max_queue < 1:
            raise InvalidParameterError(
                "max_batch and max_queue must be >= 1")
        if pipeline_depth is not None and pipeline_depth < 1:
            raise InvalidParameterError("pipeline_depth must be >= 1")
        if pin_after < 0 or max_pinned_shapes < 1:
            raise InvalidParameterError(
                "pin_after must be >= 0 and max_pinned_shapes >= 1")
        self.registry = registry
        self.metrics = metrics if metrics is not None else ServeMetrics()
        # The device pool: ``None`` keeps every execution on the default
        # placement (single-accelerator process); ``"all"`` spreads
        # requests round-robin over every visible device — fused buckets
        # land whole on one device, serial buckets fan their requests
        # across the pool. On a multi-chip host this is the throughput
        # multiplier a registry + one queue cannot provide on their own.
        if devices == "all":
            import jax
            devices = list(jax.devices())
        self._devices = list(devices) if devices else [None]
        self._rotor = 0
        self._batch_window = float(batch_window)
        self._max_batch = int(max_batch)
        self._max_queue = int(max_queue)
        self._batching = bool(batching)
        self._pin_after = int(pin_after)
        self._max_pinned = int(max_pinned_shapes)
        self._pipeline_depth = pipeline_depth
        self._shards: Dict[tuple, _Shard] = {}
        self._pending = 0
        self._high_pending = 0
        # GIL-atomic arrival counter: requests are stamped BEFORE the
        # queue lock so Future/request construction never extends the
        # lock hold; heap ties only need uniqueness + rough arrival
        # order, not lock-exact monotonicity
        self._seq = itertools.count(1)
        # per-signature pinned exact batch shapes (LRU); dispatcher
        # thread only, no lock needed
        self._pins: Dict[PlanSignature,
                         "collections.OrderedDict[int, None]"] = {}
        # staging buffer free-lists, keyed (shard key, batch shape);
        # dispatcher thread only
        self._staging: Dict[tuple, List[np.ndarray]] = {}
        self._cv = threading.Condition()
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        if autostart:
            self.start()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """Start the dispatcher thread (idempotent)."""
        with self._cv:
            if self._closed:
                raise ServeError("executor is closed")
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._dispatch_loop,
                    name="spfft-serve-dispatcher", daemon=True)
                self._thread.start()

    def close(self, drain: bool = True) -> None:
        """Stop accepting work and shut the dispatcher down. With
        ``drain`` (default) queued requests execute first; otherwise
        they fail with ``ServeError``."""
        dropped: List[_Request] = []
        with self._cv:
            if self._closed:
                return
            self._closed = True
            if not drain:
                for shard in self._shards.values():
                    for lane in (shard.high, shard.normal):
                        dropped.extend(req for _, _, req in lane)
                        lane.clear()
                self._pending = 0
                self._high_pending = 0
            self._cv.notify_all()
            thread = self._thread
        for req in dropped:  # resolve futures outside the lock
            req.future.set_exception(
                ServeError("executor closed before dispatch"))
        if thread is None:
            # never started: drain synchronously so no future is left
            # forever-pending
            self._drain_once()
        else:
            thread.join()

    def __enter__(self) -> "ServeExecutor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- submission --------------------------------------------------------
    def submit(self, signature: PlanSignature, values,
               kind: str = "backward",
               scaling: Scaling = Scaling.NONE,
               timeout: Optional[float] = None,
               priority: str = "normal") -> Future:
        """Queue one transform request; returns its Future.

        ``kind`` is ``"backward"`` (values -> space) or ``"forward"``
        (space -> values, with ``scaling``). ``timeout`` (seconds) sets
        a deadline: requests still queued when it elapses fail with
        ``DeadlineExpiredError`` instead of executing, and queued
        requests are served earliest-deadline-first within their lane.
        ``priority`` is ``"normal"`` or ``"high"`` — high-lane requests
        are served before any normal-lane work and preempt a forming
        normal bucket's batching window. Raises ``QueueFullError``
        immediately when the bounded queue is at capacity and
        ``InvalidParameterError`` for signatures the registry does not
        hold."""
        if kind not in ("backward", "forward"):
            raise InvalidParameterError(
                f"kind must be 'backward' or 'forward', got {kind!r}")
        if priority not in _PRIORITIES:
            raise InvalidParameterError(
                f"priority must be 'normal' or 'high', got {priority!r}")
        scaling = Scaling(scaling)
        plan = self.registry.get(signature)
        if plan is None:
            raise InvalidParameterError(
                f"signature not in registry (warm up first): {signature}")
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        key = (signature, kind, scaling)
        req = _Request(key, plan, kind, values, scaling, deadline,
                       priority, next(self._seq))
        entry = (deadline if deadline is not None else math.inf,
                 req.seq, req)
        with self._cv:
            if self._closed:
                raise ServeError("executor is closed")
            if self._pending >= self._max_queue:
                full = True
            else:
                full = False
                shard = self._shards.get(key)
                if shard is None:
                    shard = self._shards[key] = _Shard(key, plan)
                lane = shard.high if priority == "high" else shard.normal
                heapq.heappush(lane, entry)
                self._pending += 1
                if priority == "high":
                    self._high_pending += 1
                depth = self._pending
                self._cv.notify_all()
        # metric recording outside the queue lock
        if full:
            self.metrics.record_reject_queue_full()
            raise QueueFullError(
                f"serving queue full ({self._max_queue} requests) — "
                f"backpressure: retry later or raise max_queue")
        self.metrics.record_enqueue(depth)
        return req.future

    def submit_backward(self, signature, values,
                        timeout: Optional[float] = None,
                        priority: str = "normal") -> Future:
        return self.submit(signature, values, "backward", timeout=timeout,
                           priority=priority)

    def submit_forward(self, signature, space,
                       scaling: Scaling = Scaling.NONE,
                       timeout: Optional[float] = None,
                       priority: str = "normal") -> Future:
        return self.submit(signature, space, "forward", scaling=scaling,
                           timeout=timeout, priority=priority)

    # -- scheduling (caller holds the lock) --------------------------------
    def _select_shard(self) -> Optional[_Shard]:
        """The shard whose head request is most urgent: high lane before
        normal, then earliest deadline, then arrival order. O(#active
        signatures), not O(queued requests)."""
        best = best_rank = None
        for shard in self._shards.values():
            rank = shard.head_rank()
            if rank is not None and (best_rank is None
                                     or rank < best_rank):
                best, best_rank = shard, rank
        return best

    def _pop_into(self, shard: _Shard, bucket: List[_Request],
                  limit: int) -> None:
        """Move up to ``limit - len(bucket)`` requests from the shard's
        lanes into ``bucket`` — high lane drained first, EDF order
        within each lane."""
        for lane in (shard.high, shard.normal):
            while lane and len(bucket) < limit:
                _, _, req = heapq.heappop(lane)
                bucket.append(req)
                self._pending -= 1
                if req.priority == "high":
                    self._high_pending -= 1

    def _earliest_deadline(self) -> float:
        """The soonest deadline among ALL queued requests (inf when
        none) — lane heads are heap minima, so this is O(#shards)."""
        d = math.inf
        for shard in self._shards.values():
            for lane in (shard.high, shard.normal):
                if lane and lane[0][0] < d:
                    d = lane[0][0]
        return d

    # -- dispatch ----------------------------------------------------------
    def _fill_bucket(self, shard: _Shard, bucket: List[_Request]) -> None:
        """Wait out the batching window, absorbing same-key arrivals
        into ``bucket`` until it is full or the window closes. The
        window closes EARLY when a high-priority request lands for
        another signature or a queued deadline is about to expire —
        bucket formation never holds urgent work hostage."""
        until = time.monotonic() + self._batch_window
        while len(bucket) < self._max_batch:
            with self._cv:
                self._pop_into(shard, bucket, self._max_batch)
                if len(bucket) >= self._max_batch or self._closed:
                    return
                if self._high_pending:
                    return  # high work for another key: close early
                now = time.monotonic()
                wait = until - now
                d = self._earliest_deadline()
                if d - now < wait:
                    wait = d - now  # EDF: serve it before it expires
                if wait <= 0:
                    return
                self._cv.wait(wait)

    def _pipeline_slots(self) -> int:
        """In-flight bucket window for the dispatch loop. On an
        ACCELERATOR backend it is one slot deeper than the device pool:
        pool-size buckets overlap across devices, and the extra slot
        double-buffers the host side — the dispatcher stacks and
        dispatches bucket N+1 while the device still executes bucket N.
        On the CPU backend the extra slot is a measured LOSS (two
        buckets then compute concurrently in XLA:CPU's shared intra-op
        thread pool and thrash it — the round-6 finding that serialised
        the pool in the first place; re-measured this round at -15% on
        the same-signature trace), so CPU keeps the strict
        dispatch-then-resolve window of pool size. ``pipeline_depth``
        overrides the choice."""
        if self._pipeline_depth is not None:
            return self._pipeline_depth
        import jax
        extra = 0 if jax.default_backend() == "cpu" else 1
        return len(self._devices) + extra

    def _dispatch_loop(self) -> None:
        # Bounded in-flight pipelining (see _pipeline_slots): futures
        # resolve in _finish, after materialisation.
        inflight: "collections.deque" = collections.deque()
        depth = self._pipeline_slots()
        while True:
            shard = bucket = None
            with self._cv:
                if self._pending:
                    shard = self._select_shard()
                    bucket = []
                    self._pop_into(shard, bucket, self._max_batch)
                    depth_now = self._pending
                elif inflight:
                    pass  # fall through: flush one in-flight bucket
                elif self._closed:
                    return
                else:
                    self._cv.wait()
                    continue
            if bucket is None:
                self._finish(*inflight.popleft())
                continue
            self.metrics.record_dequeue(depth_now)
            # Wait out the batching window only on a TRICKLE (nothing
            # else queued after the take): under backlog the queued
            # requests are already late and a window wait just adds
            # latency without improving fill — the take itself drains
            # every same-key request the shard holds.
            if len(bucket) < self._max_batch and depth_now == 0 \
                    and self._batching and self._batch_window > 0 \
                    and not self._closed:
                self._fill_bucket(shard, bucket)
            work = self._execute(shard, bucket)
            if work is not None:
                inflight.append(work)
            while len(inflight) >= depth:
                self._finish(*inflight.popleft())

    def _drain_once(self) -> None:
        """Synchronous drain (close() on a never-started executor, and
        the bench CLI's deterministic ``--smoke`` waves): buckets form
        from whatever is queued, no windows, no pipelining."""
        while True:
            with self._cv:
                if not self._pending:
                    return
                shard = self._select_shard()
                bucket: List[_Request] = []
                self._pop_into(shard, bucket, self._max_batch)
                depth_now = self._pending
            self.metrics.record_dequeue(depth_now)
            work = self._execute(shard, bucket)
            if work is not None:
                self._finish(*work)

    # -- execution ---------------------------------------------------------
    def _next_device(self):
        d = self._devices[self._rotor % len(self._devices)]
        self._rotor += 1
        return d

    def prewarm(self, signature: PlanSignature,
                scaling: Scaling = Scaling.NONE,
                batch_sizes=()) -> None:
        """Compile/warm every executable this executor can dispatch for
        ``signature``: the serial backward/forward pair plus each fused
        batch shape of the planned-batch ladder — plus any
        ``batch_sizes`` a caller expects to PIN (exact shapes the
        adaptive observer would otherwise compile on first pinned
        dispatch) — on EVERY pool device (jit caches one executable per
        device). Call once per signature before traffic — on TPU this is
        where the persistent compilation cache pays out; without it the
        first bucket per (shape, device, ladder size) eats a compile
        inside a request's latency."""
        plan = self.registry.get(signature)
        if plan is None:
            raise InvalidParameterError(
                f"signature not in registry: {signature}")
        import jax
        nv = plan.index_plan.num_values
        zeros = (np.zeros((nv, 2), np.float32)
                 if plan.precision == "single"
                 else np.zeros(nv, np.complex128))
        ladder = sorted({self._padded_size(b)
                         for b in range(2, self._max_batch + 1)}
                        | {int(b) for b in batch_sizes if int(b) >= 2})
        for device in self._devices:
            space = plan.backward(zeros, device=device)
            out = [plan.forward(space, scaling, device=device)]
            if self._batching:
                for size in ladder:
                    if not fusion_eligible(plan, size):
                        continue
                    out.append(plan.backward_batched(
                        [zeros] * size, device=device))
                    out.append(plan.forward_batched(
                        [space] * size, scaling, device=device))
            jax.block_until_ready(out)

    def _padded_size(self, b: int) -> int:
        """The fallback batch ladder (``multi.planned_batch_size``):
        smallest power of two >= ``b``, capped at ``max_batch``."""
        return planned_batch_size(b, self._max_batch)

    def _dispatch_shape(self, shard: _Shard, b: int) -> Tuple[int, bool]:
        """The batch shape a fused bucket of ``b`` live rows dispatches
        at, and whether that shape is exact (pinned or ladder-exact).

        The observer pins ``b`` once it repeats ``pin_after`` times
        consecutively; pinned shapes live in a per-signature LRU capped
        at ``max_pinned_shapes``. Churny traffic (no streak) falls back
        to the pow2 ladder, so the compiled-shape count stays bounded
        either way. Dispatcher thread only — no lock."""
        ladder = self._padded_size(b)
        if ladder == b:
            # ladder already exact: zero pad rows for free, no pin
            # needed (and none counted — pinned_batches reads the
            # adaptive path only)
            return b, False
        if self._pin_after <= 0:
            return ladder, False
        if b == shard.last_size:
            shard.streak += 1
        else:
            shard.last_size = b
            shard.streak = 1
        pins = self._pins.get(shard.key[0])
        if pins is not None and b in pins:
            pins.move_to_end(b)
            return b, True
        if shard.streak >= self._pin_after:
            if pins is None:
                pins = self._pins[shard.key[0]] = collections.OrderedDict()
            pins[b] = None
            while len(pins) > self._max_pinned:
                pins.popitem(last=False)
            return b, True
        return ladder, False

    # -- staging -----------------------------------------------------------
    def _row_template(self, shard: _Shard):
        if not shard.template_ready:
            shard.row_template = shard.plan.batch_row_template(
                "values" if shard.key[1] == "backward" else "space")
            shard.template_ready = True
        return shard.row_template

    def _stage(self, shard: _Shard, live: List[_Request], shape: int):
        """Stack ``live`` payloads (plus pad rows up to ``shape``) into
        a reusable preallocated host buffer when every payload coerces
        to a host row of the plan's template — one allocation per
        (shard, shape) steady-state, one device transfer per bucket.
        Returns ``(batch_arg, buffer)``; ``buffer`` is None on the
        fallback list path (device-array payloads, double-single plans),
        where the plan's own ``_stack_coerced`` handles staging.

        Buffers come from a free-list and are returned in
        :meth:`_finish` AFTER the bucket materialises — ``jnp.asarray``
        may alias host memory on the CPU backend, so a buffer is never
        rewritten while its bucket may still read it."""
        template = self._row_template(shard)
        if template is not None:
            plan, kind = shard.plan, shard.key[1]
            coerce = (plan._coerce_values if kind == "backward"
                      else plan._coerce_space)
            rows = [coerce(req.values) for req in live]
            row_shape, dtype = template
            if all(isinstance(r, np.ndarray) and r.shape == row_shape
                   and r.dtype == dtype for r in rows):
                pool_key = (shard.key, shape)
                free = self._staging.get(pool_key)
                buf = free.pop() if free else np.empty(
                    (shape,) + row_shape, dtype)
                for i, r in enumerate(rows):
                    buf[i] = r
                for j in range(len(rows), shape):
                    buf[j] = buf[0]  # pad rows repeat row 0
                return buf, buf
        values = [req.values for req in live]
        values += [values[0]] * (shape - len(values))
        return values, None

    def _release(self, shard_key, shape: int,
                 buf: Optional[np.ndarray]) -> None:
        if buf is not None:
            self._staging.setdefault((shard_key, shape), []).append(buf)

    def _execute(self, shard: _Shard, bucket: List[_Request]):
        """Deadline-check and DISPATCH one bucket. Returns ``(live,
        results, shard_key, shape, buf)`` with results possibly still
        executing (the dispatch loop pipelines them), or ``None`` when
        nothing survived the deadline check or the dispatch itself
        failed."""
        now = time.monotonic()
        live: List[_Request] = []
        expired: List[_Request] = []
        for req in bucket:
            (expired if req.deadline is not None and now > req.deadline
             else live).append(req)
        for req in expired:
            self.metrics.record_deadline_expired()
            req.future.set_exception(DeadlineExpiredError(
                f"deadline expired after "
                f"{now - req.enqueued_at:.3f}s in queue"))
        if not live:
            return None
        plan = live[0].plan
        kind = live[0].kind
        scaling = live[0].scaling
        # device pools apply to LOCAL plans only — a distributed plan
        # already spans its mesh and pins its own placement
        pooled = (self._devices != [None]
                  and isinstance(plan, TransformPlan))
        b = len(live)
        shape, exact = b, False
        fused = False
        if self._batching and b >= 2:
            shape, exact = self._dispatch_shape(shard, b)
            fused = fusion_eligible(plan, shape)
        buf = None
        t0 = time.perf_counter()
        try:
            if fused:
                # Planned-batch execution (the cuFFT idiom): dispatch at
                # the exact pinned shape when the observer has locked
                # on, else pad up to the next pow2 ladder size so only
                # O(log max_batch) batched executables ever compile per
                # plan. vmap rows are independent, so pad rows (repeats
                # of row 0) cannot perturb the live rows and results
                # stay bit-identical to serial execution. The whole
                # bucket lands on ONE pool device; successive buckets
                # rotate.
                batch_arg, buf = self._stage(shard, live, shape)
                device = self._next_device() if pooled else None
                t1 = time.perf_counter()
                if kind == "backward":
                    stacked = plan.backward_batched(batch_arg,
                                                    device=device)
                else:
                    stacked = plan.forward_batched(batch_arg, scaling,
                                                   device=device)
                results = [stacked[i] for i in range(b)]
            else:
                # serial path: dispatch every request before blocking on
                # any result (the multi.py async-overlap idiom), fanned
                # round-robin across the device pool
                t1 = t0
                shape, exact = b, False
                results = []
                for req in live:
                    device = (self._next_device()
                              if pooled else None)
                    if kind == "backward":
                        results.append(plan.backward(req.values,
                                                     device=device))
                    else:
                        results.append(plan.forward(req.values, scaling,
                                                    device=device))
        except Exception as exc:
            self._release(shard.key, shape, buf)
            done = time.monotonic()
            for req in live:
                self.metrics.record_request_done(done - req.enqueued_at,
                                                 failed=True,
                                                 priority=req.priority)
                req.future.set_exception(exc)
            return None
        t2 = time.perf_counter()
        self.metrics.record_batch(b, fused,
                                  padded_rows=shape - b if fused else 0,
                                  pinned=fused and exact,
                                  stage_s=t1 - t0, dispatch_s=t2 - t1)
        return live, results, shard.key, shape, buf

    def _finish(self, live, results, shard_key=None, shape=0,
                buf=None) -> None:
        """Materialise a dispatched bucket and resolve its futures:
        latency samples measure completion (not dispatch), and async XLA
        failures surface here as exceptions instead of poisoned arrays.
        The staging buffer returns to its free-list only now — after
        materialisation — so reuse can never race the device transfer."""
        try:
            import jax
            jax.block_until_ready(results)
        except Exception as exc:
            self._release(shard_key, shape, buf)
            done = time.monotonic()
            for req in live:
                self.metrics.record_request_done(done - req.enqueued_at,
                                                 failed=True,
                                                 priority=req.priority)
                req.future.set_exception(exc)
            return
        self._release(shard_key, shape, buf)
        done = time.monotonic()
        for req, res in zip(live, results):
            self.metrics.record_request_done(done - req.enqueued_at,
                                             priority=req.priority)
            req.future.set_result(res)

    # -- introspection -----------------------------------------------------
    def pinned_shapes(self, signature: PlanSignature) -> Tuple[int, ...]:
        """The exact batch shapes currently pinned for ``signature``
        (LRU order, oldest first). Diagnostic only — reads dispatcher-
        owned state, so values are advisory under live traffic."""
        pins = self._pins.get(signature)
        return tuple(pins) if pins else ()

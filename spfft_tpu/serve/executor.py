"""Concurrent batching executor: futures in, fused batches out.

The reference's throughput lever for many independent transforms is its
multi-transform scheduler — hand-interleaved phases of N transforms
(reference: src/spfft/multi_transform_internal.hpp:47-145), reproduced
here as ``spfft_tpu.multi``. This module turns that primitive into a
request-driven serving layer: callers ``submit(signature, values)`` from
any number of threads and get ``concurrent.futures.Future``s back; a
single dispatcher thread buckets same-signature requests that arrive
within a small time window and executes full buckets through the plan's
fused batched executables (the ``multi.py`` fused path — one vmapped
dispatch for B requests), stragglers through the ordinary serial path.

Correctness contract: any interleaving of concurrent requests produces
results BIT-IDENTICAL to running each request alone on its plan. Two
structural facts make this hold: (1) requests only share a bucket when
their signatures are equal, and equal signatures resolve to the same
plan object (registry invariant); (2) the fused batched pipeline is the
vmapped form of the serial pipeline over identical static tables —
verified bit-exact against the serial path by the tier-1 concurrency
fuzz (tests/test_serve_executor.py). The batching policy (when fusion
wins) is ``multi.fusion_eligible`` — the SAME gate ``multi_transform_*``
uses, so the serving layer degrades to serial dispatch exactly where the
library itself would.

Flow control is explicit and bounded: a fixed-capacity queue whose
overflow REJECTS with ``QueueFullError`` (backpressure the caller can
see, never silent unbounded buffering), per-request deadlines that
expire queued work with ``DeadlineExpiredError`` before it wastes device
time, and ``batching=False`` (or a fusion-ineligible regime) degrading
gracefully to serial per-request dispatch.
"""

from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import Future
from typing import Optional

from ..errors import (DeadlineExpiredError, InvalidParameterError,
                      QueueFullError, ServeError)
from ..multi import fusion_eligible
from ..types import Scaling
from .metrics import ServeMetrics
from .registry import PlanRegistry, PlanSignature

#: Default same-signature batching window (seconds): long enough to
#: collect a burst dispatched by concurrent submitters, short enough to
#: be invisible next to a single transform execution (ms-class).
DEFAULT_BATCH_WINDOW = 0.002

#: Default bucket cap — the fused-batch regime gate
#: (multi.FUSED_BATCH_MAX_GRID) bounds total work; this bounds latency
#: amplification for the first request of a burst.
DEFAULT_MAX_BATCH = 8

DEFAULT_MAX_QUEUE = 256


class _Request:
    __slots__ = ("key", "plan", "kind", "values", "scaling", "deadline",
                 "future", "enqueued_at")

    def __init__(self, key, plan, kind, values, scaling, deadline):
        self.key = key
        self.plan = plan
        self.kind = kind
        self.values = values
        self.scaling = scaling
        self.deadline = deadline
        self.future: Future = Future()
        self.enqueued_at = time.monotonic()


class ServeExecutor:
    """One dispatcher thread over a bounded request queue.

    ``registry`` resolves signatures to plans (requests for unknown
    signatures are rejected at submit time — a server warms its shapes
    up front; see ``PlanRegistry.warmup``). Use as a context manager or
    call :meth:`close` to drain and stop.

    ``autostart=False`` defers the dispatcher thread until
    :meth:`start` — used by tests (and pre-warm scripts) to stage a
    queue deterministically before any dispatch happens.
    """

    def __init__(self, registry: PlanRegistry,
                 batch_window: float = DEFAULT_BATCH_WINDOW,
                 max_batch: int = DEFAULT_MAX_BATCH,
                 max_queue: int = DEFAULT_MAX_QUEUE,
                 batching: bool = True,
                 devices=None,
                 metrics: Optional[ServeMetrics] = None,
                 autostart: bool = True):
        if max_batch < 1 or max_queue < 1:
            raise InvalidParameterError(
                "max_batch and max_queue must be >= 1")
        self.registry = registry
        self.metrics = metrics if metrics is not None else ServeMetrics()
        # The device pool: ``None`` keeps every execution on the default
        # placement (single-accelerator process); ``"all"`` spreads
        # requests round-robin over every visible device — fused buckets
        # land whole on one device, serial buckets fan their requests
        # across the pool. On a multi-chip host this is the throughput
        # multiplier a registry + one queue cannot provide on their own.
        if devices == "all":
            import jax
            devices = list(jax.devices())
        self._devices = list(devices) if devices else [None]
        self._rotor = 0
        self._batch_window = float(batch_window)
        self._max_batch = int(max_batch)
        self._max_queue = int(max_queue)
        self._batching = bool(batching)
        self._queue: "collections.deque[_Request]" = collections.deque()
        self._cv = threading.Condition()
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        if autostart:
            self.start()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """Start the dispatcher thread (idempotent)."""
        with self._cv:
            if self._closed:
                raise ServeError("executor is closed")
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._dispatch_loop,
                    name="spfft-serve-dispatcher", daemon=True)
                self._thread.start()

    def close(self, drain: bool = True) -> None:
        """Stop accepting work and shut the dispatcher down. With
        ``drain`` (default) queued requests execute first; otherwise
        they fail with ``ServeError``."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            if not drain:
                while self._queue:
                    req = self._queue.popleft()
                    req.future.set_exception(
                        ServeError("executor closed before dispatch"))
            self._cv.notify_all()
            thread = self._thread
        if thread is None:
            # never started: drain synchronously so no future is left
            # forever-pending
            self._drain_once()
        else:
            thread.join()

    def __enter__(self) -> "ServeExecutor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- submission --------------------------------------------------------
    def submit(self, signature: PlanSignature, values,
               kind: str = "backward",
               scaling: Scaling = Scaling.NONE,
               timeout: Optional[float] = None) -> Future:
        """Queue one transform request; returns its Future.

        ``kind`` is ``"backward"`` (values -> space) or ``"forward"``
        (space -> values, with ``scaling``). ``timeout`` (seconds) sets
        a deadline: requests still queued when it elapses fail with
        ``DeadlineExpiredError`` instead of executing. Raises
        ``QueueFullError`` immediately when the bounded queue is at
        capacity and ``InvalidParameterError`` for signatures the
        registry does not hold."""
        if kind not in ("backward", "forward"):
            raise InvalidParameterError(
                f"kind must be 'backward' or 'forward', got {kind!r}")
        scaling = Scaling(scaling)
        plan = self.registry.get(signature)
        if plan is None:
            raise InvalidParameterError(
                f"signature not in registry (warm up first): {signature}")
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        req = _Request((signature, kind, scaling), plan, kind, values,
                       scaling, deadline)
        with self._cv:
            if self._closed:
                raise ServeError("executor is closed")
            if len(self._queue) >= self._max_queue:
                self.metrics.record_reject_queue_full()
                raise QueueFullError(
                    f"serving queue full ({self._max_queue} requests) — "
                    f"backpressure: retry later or raise max_queue")
            self._queue.append(req)
            self.metrics.record_enqueue(len(self._queue))
            self._cv.notify_all()
        return req.future

    def submit_backward(self, signature, values,
                        timeout: Optional[float] = None) -> Future:
        return self.submit(signature, values, "backward", timeout=timeout)

    def submit_forward(self, signature, space,
                       scaling: Scaling = Scaling.NONE,
                       timeout: Optional[float] = None) -> Future:
        return self.submit(signature, space, "forward", scaling=scaling,
                           timeout=timeout)

    # -- dispatch ----------------------------------------------------------
    def _take_bucket(self):
        """Pop the oldest request plus every same-key request currently
        queued (caller holds the lock), up to ``max_batch``."""
        head = self._queue.popleft()
        bucket = [head]
        if self._max_batch > 1:
            keep = collections.deque()
            while self._queue and len(bucket) < self._max_batch:
                req = self._queue.popleft()
                (bucket if req.key == head.key else keep).append(req)
            keep.extend(self._queue)
            self._queue = keep
        self.metrics.record_dequeue(len(self._queue))
        return bucket

    def _fill_bucket(self, bucket) -> None:
        """Wait out the batching window, absorbing same-key arrivals
        into ``bucket`` until it is full or the window closes."""
        key = bucket[0].key
        until = time.monotonic() + self._batch_window
        while len(bucket) < self._max_batch:
            remaining = until - time.monotonic()
            if remaining <= 0:
                return
            with self._cv:
                matched = False
                keep = collections.deque()
                while self._queue and len(bucket) < self._max_batch:
                    req = self._queue.popleft()
                    if req.key == key:
                        bucket.append(req)
                        matched = True
                    else:
                        keep.append(req)
                keep.extend(self._queue)
                self._queue = keep
                self.metrics.record_dequeue(len(self._queue))
                if len(bucket) >= self._max_batch or self._closed:
                    return
                if not matched:
                    self._cv.wait(remaining)

    def _dispatch_loop(self) -> None:
        # Bounded in-flight pipelining: up to pool-size buckets stay
        # dispatched-but-unresolved, so a device pool genuinely overlaps
        # bucket executions (a block per bucket would serialise the pool
        # down to one device's throughput). Futures resolve in _finish,
        # after materialisation — depth 1 (no pool) degrades to the
        # strict dispatch-then-block loop.
        inflight: "collections.deque" = collections.deque()
        depth = len(self._devices)
        while True:
            bucket = None
            with self._cv:
                if self._queue:
                    bucket = self._take_bucket()
                elif inflight:
                    pass  # fall through: flush one in-flight bucket
                elif self._closed:
                    return
                else:
                    self._cv.wait()
                    continue
            if bucket is None:
                self._finish(*inflight.popleft())
                continue
            # Wait out the batching window only on a TRICKLE (queue
            # empty after the take): under backlog the queued requests
            # are already late and a window wait just adds latency
            # without improving fill — the take itself scavenges every
            # same-key request the backlog holds.
            with self._cv:
                trickle = not self._queue
            if len(bucket) < self._max_batch and trickle \
                    and self._batching and self._batch_window > 0 \
                    and not self._closed:
                self._fill_bucket(bucket)
            work = self._execute(bucket)
            if work is not None:
                inflight.append(work)
            while len(inflight) >= depth:
                self._finish(*inflight.popleft())

    def _drain_once(self) -> None:
        """Synchronous drain for the never-started case (close() on an
        ``autostart=False`` executor that queued work)."""
        while True:
            with self._cv:
                if not self._queue:
                    return
                bucket = self._take_bucket()
            work = self._execute(bucket)
            if work is not None:
                self._finish(*work)

    # -- execution ---------------------------------------------------------
    def _next_device(self):
        d = self._devices[self._rotor % len(self._devices)]
        self._rotor += 1
        return d

    def prewarm(self, signature: PlanSignature,
                scaling: Scaling = Scaling.NONE) -> None:
        """Compile/warm every executable this executor can dispatch for
        ``signature``: the serial backward/forward pair plus each fused
        batch shape of the planned-batch ladder, on EVERY pool device
        (jit caches one executable per device). Call once per signature
        before traffic — on TPU this is where the persistent compilation
        cache pays out; without it the first bucket per (shape, device,
        ladder size) eats a compile inside a request's latency."""
        plan = self.registry.get(signature)
        if plan is None:
            raise InvalidParameterError(
                f"signature not in registry: {signature}")
        import jax
        import numpy as np
        nv = plan.index_plan.num_values
        zeros = (np.zeros((nv, 2), np.float32)
                 if plan.precision == "single"
                 else np.zeros(nv, np.complex128))
        ladder = sorted({self._padded_size(b)
                         for b in range(2, self._max_batch + 1)})
        for device in self._devices:
            space = plan.backward(zeros, device=device)
            out = [plan.forward(space, scaling, device=device)]
            if self._batching:
                for size in ladder:
                    if not fusion_eligible(plan, size):
                        continue
                    out.append(plan.backward_batched(
                        [zeros] * size, device=device))
                    out.append(plan.forward_batched(
                        [space] * size, scaling, device=device))
            jax.block_until_ready(out)

    def _padded_size(self, b: int) -> int:
        """The batch ladder: the smallest power of two >= ``b``, capped
        at ``max_batch``. Bounds the set of compiled batch shapes per
        plan while wasting at most 2x compute on pad rows."""
        p = 2
        while p < b and p < self._max_batch:
            p *= 2
        return min(p, self._max_batch)

    def _execute(self, bucket):
        """Deadline-check and DISPATCH one bucket. Returns ``(live,
        results)`` with results possibly still executing (the dispatch
        loop pipelines them), or ``None`` when nothing survived the
        deadline check or the dispatch itself failed."""
        now = time.monotonic()
        live = []
        for req in bucket:
            if req.deadline is not None and now > req.deadline:
                self.metrics.record_deadline_expired()
                req.future.set_exception(DeadlineExpiredError(
                    f"deadline expired after "
                    f"{now - req.enqueued_at:.3f}s in queue"))
            else:
                live.append(req)
        if not live:
            return None
        plan = live[0].plan
        kind = live[0].kind
        scaling = live[0].scaling
        # device pools apply to LOCAL plans only — a distributed plan
        # already spans its mesh and pins its own placement
        from ..plan import TransformPlan
        pooled = (self._devices != [None]
                  and isinstance(plan, TransformPlan))
        padded = self._padded_size(len(live))
        fused = (self._batching and len(live) >= 2
                 and fusion_eligible(plan, padded))
        self.metrics.record_batch(len(live), fused)
        try:
            if fused:
                # Planned-batch execution (the cuFFT idiom): pad the
                # bucket up to the next ladder size so only
                # O(log max_batch) batched executables ever compile per
                # plan, instead of one retrace per distinct bucket size.
                # vmap rows are independent, so pad rows (repeats of row
                # 0) cannot perturb the live rows and results stay
                # bit-identical to serial execution. The whole bucket
                # lands on ONE pool device; successive buckets rotate.
                values = [r.values for r in live]
                values += [values[0]] * (padded - len(values))
                device = self._next_device() if pooled else None
                if kind == "backward":
                    stacked = plan.backward_batched(values, device=device)
                else:
                    stacked = plan.forward_batched(values, scaling,
                                                   device=device)
                results = [stacked[i] for i in range(len(live))]
            else:
                # serial path: dispatch every request before blocking on
                # any result (the multi.py async-overlap idiom), fanned
                # round-robin across the device pool
                results = []
                for req in live:
                    device = (self._next_device()
                              if pooled else None)
                    if kind == "backward":
                        results.append(plan.backward(req.values,
                                                     device=device))
                    else:
                        results.append(plan.forward(req.values, scaling,
                                                    device=device))
        except Exception as exc:
            done = time.monotonic()
            for req in live:
                self.metrics.record_request_done(done - req.enqueued_at,
                                                 failed=True)
                req.future.set_exception(exc)
            return None
        return live, results

    def _finish(self, live, results) -> None:
        """Materialise a dispatched bucket and resolve its futures:
        latency samples measure completion (not dispatch), and async XLA
        failures surface here as exceptions instead of poisoned
        arrays."""
        try:
            import jax
            jax.block_until_ready(results)
        except Exception as exc:
            done = time.monotonic()
            for req in live:
                self.metrics.record_request_done(done - req.enqueued_at,
                                                 failed=True)
                req.future.set_exception(exc)
            return
        done = time.monotonic()
        for req, res in zip(live, results):
            self.metrics.record_request_done(done - req.enqueued_at)
            req.future.set_result(res)

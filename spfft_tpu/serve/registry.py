"""Plan registry: a byte-aware bounded LRU of compiled transform plans.

The serving story's first cost is plan construction: ~0.35 s per cold
256^3 plan on this container (r05 bench ``plan_s``), and every caller of
the library API today hand-builds its own ``TransformPlan``. A server
handling heavy traffic sees the same few transform shapes over and over
— the right structure is a process-wide registry keyed by a CANONICAL
plan signature, so the first request for a shape pays plan construction
(and, on TPU, the XLA compile — already softened by the persistent
compilation cache ``utils.platform.enable_persistent_compilation_cache``
that every plan construction enables) and every later request reuses the
live plan object.

The registry is bounded two ways, mirroring the matrix-cache policy in
``ops.dft`` (round-4/5 advisor findings on unbounded caches in
plan-churning servers): an entry-count cap and a BYTE budget over each
plan's estimated resident footprint (``TransformPlan.
estimated_device_bytes`` — index tables dominate; a 256^3
spherical-cutoff plan pins ~100 MB of device tables). Eviction is
oldest-use-first and never evicts the entry being inserted.

``get_or_build`` resolves a REPEATED raw request shape without touching
``build_index_plan`` at all: a bounded raw-bytes -> signature memo
(exact byte comparison against stored snapshots — see ``_memo_key`` for
why comparison beats hashing) short-circuits straight to the resident
plan; index-table construction is milliseconds-to-seconds where the
serving hot-path budget is sub-millisecond. Concurrent first requests
for one shape serialise through a per-shape singleflight lock, so a
cold popular shape builds exactly once under a thundering herd.

Signature canonicalisation: two requests address the same plan iff their
(dims, transform type, precision, scaling, device count) match AND their
sparse frequency sets match *in caller order* — the value array a caller
submits is positional, so order is part of the contract (a reordered
triplet set is a DIFFERENT plan whose results are permuted). The digest
is computed over the index plan's ``value_indices`` + ``stick_keys``,
which encode exactly (storage triplet, caller position) — invariant to
triplet *representation* (centered vs wrapped negative indices digest
identically) but not to order.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from .. import faults as _faults
from .. import obs as _obs
from ..errors import InvalidParameterError
from ..indexing import IndexPlan, build_index_plan
from ..plan import TransformPlan
from ..types import Scaling, TransformType


def index_digest(index_plan: IndexPlan) -> str:
    """Canonical digest of one sparse frequency set in caller order
    (see module docstring for why order is part of the identity)."""
    h = hashlib.sha256()
    h.update(np.asarray(
        [index_plan.dim_x, index_plan.dim_y, index_plan.dim_z],
        np.int64).tobytes())
    h.update(index_plan.transform_type.value.encode())
    h.update(np.ascontiguousarray(
        index_plan.value_indices.astype(np.int64)).tobytes())
    h.update(np.ascontiguousarray(
        index_plan.stick_keys.astype(np.int64)).tobytes())
    if index_plan.value_conj is not None:
        # hermitian x < 0 folding: the conj mask changes execution
        # (boundary sign flips), so two plans differing only in it must
        # never share an artifact; unfolded plans hash exactly as before
        h.update(np.ascontiguousarray(
            index_plan.value_conj.astype(np.uint8)).tobytes())
    return h.hexdigest()


@dataclasses.dataclass(frozen=True)
class PlanSignature:
    """Canonical, hashable identity of one servable transform: dims,
    sparse-index digest, transform type, precision, scaling and device
    count (the fields the ISSUE contract names). Requests carrying equal
    signatures are guaranteed to be answerable by one plan object — the
    property the executor's same-signature batching relies on."""

    transform_type: str     # TransformType.value
    dim_x: int
    dim_y: int
    dim_z: int
    index_digest: str
    precision: str
    scaling: str            # Scaling.value
    device_count: int

    @classmethod
    def of_plan(cls, plan: TransformPlan,
                scaling: Scaling = Scaling.NONE) -> "PlanSignature":
        """The signature of an already-built local plan (used to seed a
        registry with externally constructed plans)."""
        p = plan.index_plan
        return cls(p.transform_type.value, p.dim_x, p.dim_y, p.dim_z,
                   index_digest(p), plan.precision,
                   Scaling(scaling).value, 1)


def signature_for(transform_type: TransformType, dim_x: int, dim_y: int,
                  dim_z: int, triplets,
                  precision: str = "single",
                  scaling: Scaling = Scaling.NONE,
                  device_count: int = 1) -> PlanSignature:
    """Compute the canonical signature for a raw triplet set without
    building a compiled plan (index-table construction only — numpy,
    milliseconds)."""
    ip = build_index_plan(TransformType(transform_type), dim_x, dim_y,
                          dim_z, np.asarray(triplets))
    return PlanSignature(TransformType(transform_type).value,
                         dim_x, dim_y, dim_z, index_digest(ip),
                         precision, Scaling(scaling).value,
                         int(device_count))


#: Default registry bounds — owned by the control plane since round 11
#: (KNOB_SPECS "registry_max_bytes"/"registry_max_plans"): 2 GiB of
#: estimated plan residency covers a dozen 256^3-class plans or
#: hundreds of small ones; a handful of live shapes is the realistic
#: serving mix (SCF codes cycle 1-3 geometries). Constructor ``None``
#: resolves through the process config (the boot artifact applies).
DEFAULT_MAX_BYTES = 2 * 1024 ** 3
DEFAULT_MAX_PLANS = 32


def _memo_key(transform_type: TransformType, dim_x: int, dim_y: int,
              dim_z: int, triplets: np.ndarray, precision: str,
              scaling: Scaling) -> tuple:
    """Scalar bucket key of a RAW request shape for the get_or_build
    memo. Deliberately EXCLUDES the triplet contents: candidate entries
    under one key are verified by exact byte comparison
    (``np.array_equal``) against a stored snapshot instead of a content
    digest — a vectorised memcmp is ~7x cheaper than sha256 over the
    same bytes (measured: 0.28 ms vs 2.1 ms on a 209k-triplet set) and
    carries zero collision risk, which a truncated/cheap hash could not
    guarantee without exactly this comparison anyway. Unlike the
    canonical ``PlanSignature`` digest the memo is NOT representation
    invariant (centered and wrapped spellings of one sparse set occupy
    two memo slots) — both slots point at the SAME canonical
    signature."""
    return (TransformType(transform_type).value, dim_x, dim_y, dim_z,
            precision, Scaling(scaling).value, triplets.shape,
            triplets.dtype.str)


#: Byte budget for stored triplet snapshots in the get_or_build memo —
#: 64 MB holds ~25 snapshots of 256^3-spherical-cutoff size, far beyond
#: the realistic count of live request shapes.
SIG_MEMO_MAX_BYTES = 64 * 1024 ** 2


class _BuildFlight:
    """One in-flight singleflight build: waiters block on ``done`` and
    read ``exc`` — a failed build releases every waiter at once with
    the builder's exception (never a wedge of serial re-builds), a
    successful one sends them back through the memo fast path."""

    __slots__ = ("done", "exc")

    def __init__(self):
        self.done = threading.Event()
        self.exc: BaseException = None


class PlanRegistry:
    """Thread-safe byte-aware bounded LRU of ``TransformPlan``s with
    hit/miss/eviction counters and explicit warmup/prefetch.

    ``get_or_build`` is the serving entry point: signature computed from
    the caller's triplets, registry consulted, plan constructed on miss.
    ``warmup`` prefetches a list of shapes before traffic arrives — with
    ``compile=True`` it also executes one zero-valued backward per plan
    so the jit trace/compile (or persistent-cache load) happens at
    warmup time, not on the first real request.
    """

    def __init__(self, max_bytes: Optional[int] = None,
                 max_plans: Optional[int] = None,
                 store=None):
        if max_bytes is None or max_plans is None:
            from ..control.config import global_config
            cfg = global_config()
            if max_bytes is None:
                max_bytes = cfg.registry_max_bytes
            if max_plans is None:
                max_plans = cfg.registry_max_plans
        if max_plans < 1:
            raise InvalidParameterError("max_plans must be >= 1")
        # The persistent plan-artifact tier below the in-memory LRU
        # (spfft_tpu.serve.store): a ``PlanArtifactStore``, a path
        # string, ``None`` (resolve the process default — the config's
        # plan_store_path or SPFFT_TPU_PLAN_STORE; usually disabled) or
        # ``False`` to force the tier off. Read-through on miss (a warm
        # load counts NO build), write-behind spill on build.
        if store is False:
            self._disk = None
        elif store is None:
            from .store import default_store
            self._disk = default_store()
        elif isinstance(store, str):
            from .store import PlanArtifactStore
            self._disk = PlanArtifactStore(store)
        else:
            self._disk = store
        self._max_bytes = int(max_bytes)
        self._max_plans = int(max_plans)
        #: guarded by _lock
        self._store: "collections.OrderedDict[PlanSignature, Tuple[TransformPlan, int]]" = \
            collections.OrderedDict()
        self._bytes = 0      #: guarded by _lock
        self._lock = threading.Lock()
        self._hits = 0       #: guarded by _lock
        self._misses = 0     #: guarded by _lock
        self._evictions = 0  #: guarded by _lock
        self._builds = 0     #: guarded by _lock
        self._fast_hits = 0  #: guarded by _lock
        # raw-bytes -> canonical-signature memo (the get_or_build fast
        # path: a hit skips build_index_plan entirely). Keyed by the
        # scalar request tuple; each key holds (triplet snapshot, sig)
        # candidates verified by exact byte comparison. Bounded by
        # entry count AND snapshot bytes. Per-key singleflight build
        # locks serialise concurrent misses (one build per shape).
        #: guarded by _lock
        self._sig_memo: "collections.OrderedDict[tuple, List[Tuple[np.ndarray, PlanSignature]]]" = \
            collections.OrderedDict()
        self._sig_memo_cap = max(64, 4 * self._max_plans)
        self._sig_memo_bytes = 0  #: guarded by _lock
        self._build_flights: Dict[tuple, "_BuildFlight"] = {}  #: guarded by _lock
        self._build_failures = 0  #: guarded by _lock
        self._store_hits = 0      #: guarded by _lock
        self._store_misses = 0    #: guarded by _lock
        self._store_spills = 0    #: guarded by _lock

    @property
    def store(self):
        """The attached persistent artifact tier, or None."""
        return self._disk

    # -- lookup ------------------------------------------------------------
    def _get_memory(self,
                    signature: PlanSignature) -> Optional[TransformPlan]:
        """LRU-only lookup (counts hit/miss, no disk tier) — the
        in-memory half of :meth:`get`."""
        with self._lock:
            entry = self._store.get(signature)
            if entry is not None:
                self._hits += 1
                self._store.move_to_end(signature)
                return entry[0]
            self._misses += 1
            return None

    def get(self, signature: PlanSignature) -> Optional[TransformPlan]:
        """The plan for ``signature``, marking it most-recently-used —
        or None (counted as a miss). With a disk tier attached, an LRU
        miss falls through to the artifact store (a replacement process
        can answer signature-addressed traffic it has never built):
        a warm load inserts into the LRU and returns the plan; the
        counted miss stands (``store_hits`` disambiguates how the miss
        was then resolved)."""
        plan = self._get_memory(signature)
        if plan is not None:
            return plan
        if self._disk is None:
            return None
        loaded = self._disk.load_signature(signature)
        if loaded is None:
            return None
        sig, plan = loaded
        with self._lock:
            self._store_hits += 1
        self.put(sig, plan)
        return plan

    def signatures(self) -> List[PlanSignature]:
        """Snapshot of the in-memory tier's signatures, LRU order
        (oldest first), with no counter side effects — the pod
        frontend's reconciliation input (every host must hold the same
        set)."""
        with self._lock:
            return list(self._store)

    def __contains__(self, signature: PlanSignature) -> bool:
        with self._lock:  # no counter side effects
            return signature in self._store

    def __len__(self) -> int:
        with self._lock:
            return len(self._store)

    # -- insertion ---------------------------------------------------------
    def put(self, signature: PlanSignature, plan: TransformPlan) -> None:
        """Insert (or refresh) a plan under ``signature`` and evict
        oldest-first past the byte/count budgets. The inserted entry
        itself is never evicted, so one over-budget plan still serves."""
        nbytes = int(plan.estimated_device_bytes())
        with self._lock:
            old = self._store.pop(signature, None)
            if old is not None:
                self._bytes -= old[1]
            self._store[signature] = (plan, nbytes)
            self._bytes += nbytes
            while len(self._store) > 1 \
                    and (self._bytes > self._max_bytes
                         or len(self._store) > self._max_plans):
                _, (_, b) = self._store.popitem(last=False)
                self._bytes -= b
                self._evictions += 1

    # lock: holds(_lock)
    def _fast_lookup_locked(self, memo_key, arr: np.ndarray):
        """Memoed (signature, plan) for a raw request, or None. Caller
        holds the lock. Candidates under the key are verified by exact
        byte comparison against their stored snapshot — the caller's
        array either IS the remembered request shape or it is not; no
        hash, no collisions. A verified hit whose plan was evicted falls
        through to the slow path (the index plan must be rebuilt to
        reconstruct the evicted TransformPlan)."""
        candidates = self._sig_memo.get(memo_key)
        if candidates is None:
            return None
        for stored, sig in candidates:
            if np.array_equal(arr, stored):
                self._sig_memo.move_to_end(memo_key)
                entry = self._store.get(sig)
                if entry is None:
                    return None
                self._hits += 1
                self._fast_hits += 1
                self._store.move_to_end(sig)
                return sig, entry[0]
        return None

    def _memoize(self, memo_key, arr: np.ndarray,
                 sig: PlanSignature) -> None:
        # snapshot the caller's bytes: later mutation of their array
        # must not corrupt the memo's ground truth
        stored = np.ascontiguousarray(arr).copy()
        with self._lock:
            candidates = self._sig_memo.setdefault(memo_key, [])
            if any(np.array_equal(stored, s) for s, _ in candidates):
                return  # raced builder already memoized these bytes
            candidates.append((stored, sig))
            self._sig_memo.move_to_end(memo_key)
            self._sig_memo_bytes += stored.nbytes
            while len(self._sig_memo) > 1 \
                    and (len(self._sig_memo) > self._sig_memo_cap
                         or self._sig_memo_bytes > SIG_MEMO_MAX_BYTES):
                _, dropped = self._sig_memo.popitem(last=False)
                self._sig_memo_bytes -= sum(s.nbytes
                                            for s, _ in dropped)

    def get_or_build(self, transform_type: TransformType, dim_x: int,
                     dim_y: int, dim_z: int, triplets,
                     precision: str = "single",
                     scaling: Scaling = Scaling.NONE,
                     **plan_kwargs) -> Tuple[PlanSignature, TransformPlan]:
        """Resolve (signature, plan) for a raw request shape, building
        and registering the plan on a miss. ``plan_kwargs`` pass through
        to ``TransformPlan`` (use_pallas, donate_inputs, max_rel_error,
        device_double).

        Two hot-path properties (the serving layer's zero-rebuild
        contract): a REPEATED request shape resolves through a raw-bytes
        -> signature memo and never touches ``build_index_plan`` (which
        is milliseconds-to-seconds where the serving hot path is
        microseconds), and concurrent first requests for the SAME shape
        serialise through a per-shape singleflight lock so the index
        plan and TransformPlan build exactly once instead of N times
        (the dogpile). Index tables are built once and shared between
        the digest and the plan."""
        arr = np.asarray(triplets)
        memo_key = _memo_key(transform_type, dim_x, dim_y, dim_z, arr,
                             precision, scaling)
        while True:
            with self._lock:
                fast = self._fast_lookup_locked(memo_key, arr)
                if fast is None:
                    flight = self._build_flights.get(memo_key)
                    owner = flight is None
                    if owner:
                        flight = self._build_flights[memo_key] = \
                            _BuildFlight()
            if fast is not None:
                # surface background-builder DEATH at resolution time
                # instead of on the first request (round-14 fix) —
                # non-blocking (and off the registry lock): a live
                # build is never waited on here
                fast[1].check_build()
                return fast
            if owner:
                break
            # Follower: wait for the in-flight build, sharing its
            # OUTCOME either way. A failed build propagates the
            # builder's exception to every waiter IMMEDIATELY — the old
            # per-lock scheme promoted each waiter to builder in turn,
            # so N waiters behind one broken shape serialised N
            # expensive failing builds before the last caller saw the
            # error (a wedge under a thundering herd). A success loops
            # back to the fast path (counted as a hit); only a caller
            # arriving AFTER the failed flight retires retries the
            # build fresh.
            flight.done.wait()
            if flight.exc is not None:
                raise flight.exc
        try:
            # the disk tier, consulted BEFORE any index-table work: a
            # warm artifact resolves the raw request through its alias
            # (triplet-byte digest), reconstructs the plan with zero
            # builds, and enters the LRU + memo like any other plan
            if self._disk is not None:
                loaded = self._disk.load_for_request(
                    transform_type, dim_x, dim_y, dim_z, arr,
                    precision, scaling, plan_kwargs=plan_kwargs)
                if loaded is not None:
                    sig, plan = loaded
                    with self._lock:
                        self._store_hits += 1
                    self.put(sig, plan)
                    self._memoize(memo_key, arr, sig)
                    return sig, plan
                with self._lock:
                    self._store_misses += 1
            t_build = time.perf_counter()
            _faults.check_site("registry.build")
            ip = build_index_plan(TransformType(transform_type), dim_x,
                                  dim_y, dim_z, arr)
            sig = PlanSignature(TransformType(transform_type).value,
                                dim_x, dim_y, dim_z, index_digest(ip),
                                precision, Scaling(scaling).value, 1)
            plan = self._get_memory(sig)
            if plan is None and self._disk is not None:
                # a DIFFERENT spelling of this sparse set may have
                # spilled the canonical artifact (the raw alias is
                # representation sensitive, the signature is not) —
                # kwargs-aware, unlike the public get() read-through
                loaded = self._disk.load_signature(
                    sig, plan_kwargs=plan_kwargs)
                if loaded is not None:
                    _, plan = loaded
                    with self._lock:
                        self._store_hits += 1
                    self.put(sig, plan)
            if plan is None:
                plan = TransformPlan(ip, precision=precision,
                                     **plan_kwargs)
                with self._lock:
                    self._builds += 1
                self.put(sig, plan)
                # compile observability: per-signature registry build
                # (index tables + plan construction) as span/counter
                _obs.record_compile(
                    "registry_build", time.perf_counter() - t_build,
                    t_build, dims=f"{dim_x}x{dim_y}x{dim_z}",
                    precision=precision, digest=sig.index_digest[:12])
                if self._disk is not None:
                    # write-behind: serialize off the serving thread
                    self._disk.spill_async(sig, plan, arr)
                    with self._lock:
                        self._store_spills += 1
            plan.check_build()
            self._memoize(memo_key, arr, sig)
            return sig, plan
        except BaseException as exc:
            flight.exc = exc
            with self._lock:
                self._build_failures += 1
            _obs.record_event("registry.build_failure",
                              error=type(exc).__name__)
            raise
        finally:
            with self._lock:
                self._build_flights.pop(memo_key, None)
            flight.done.set()

    # -- warmup ------------------------------------------------------------
    def warmup(self, specs: Iterable[dict], compile: bool = False,
               strict: bool = True) -> List[PlanSignature]:
        """Prefetch plans for a list of shape specs before traffic.

        Each spec is either a SHAPE spec (keys ``transform_type, dim_x,
        dim_y, dim_z, triplets`` plus optional ``precision``/``scaling``
        and plan kwargs — resolved through ``get_or_build``, so the disk
        tier applies) or an ARTIFACT spec (key ``artifact`` naming a
        store key, as recorded by ``python -m spfft_tpu.serve.store
        manifest``; optional ``signature`` cross-check and
        ``plan_kwargs``; other keys are manifest metadata and ignored).
        An artifact spec that fails to load raises
        :class:`~spfft_tpu.errors.PlanArtifactError` when ``strict``
        (the default — a prewarming replacement process must not
        silently join the pool half-warm) and is skipped otherwise.

        ``compile=True`` additionally runs one zero-valued backward per
        plan so the first real request hits a fully warm executable (an
        artifact's AOT executable, the persistent XLA compilation
        cache, or a fresh compile — in that order of cheapness).
        Returns the signatures in spec order (loaded ones only when
        ``strict=False``)."""
        from ..errors import PlanArtifactError
        sigs = []
        for spec in specs:
            spec = dict(spec)
            if "artifact" in spec:
                if self._disk is None:
                    raise InvalidParameterError(
                        "warmup spec names an artifact but the "
                        "registry has no plan store attached")
                loaded = self._disk.load_key(
                    spec["artifact"],
                    plan_kwargs=spec.get("plan_kwargs"),
                    expect_sig=spec.get("signature"))
                if loaded is None:
                    if strict:
                        raise PlanArtifactError(
                            f"plan artifact {spec['artifact'][:12]}... "
                            f"failed to load during warmup (see "
                            f"spfft_store_rejects_total for the "
                            f"reason)")
                    continue
                sig, plan = loaded
                self.put(sig, plan)
            else:
                ttype = spec.pop("transform_type")
                dims = (spec.pop("dim_x"), spec.pop("dim_y"),
                        spec.pop("dim_z"))
                triplets = spec.pop("triplets")
                sig, plan = self.get_or_build(ttype, *dims, triplets,
                                              **spec)
            # warmup is the blocking pre-traffic path: join the
            # background table build so a doomed plan fails HERE, not
            # on the first request it would otherwise poison
            plan.check_build(wait=True)
            if compile:
                n = plan.index_plan.num_values
                plan.backward(np.zeros((n, 2), np.float32)
                              if plan.precision == "single"
                              else np.zeros(n, np.complex128))
            sigs.append(sig)
        return sigs

    def warmup_manifest(self, path: str, compile: bool = False,
                        strict: bool = True) -> List[PlanSignature]:
        """Boot prewarm from a recorded manifest (``python -m
        spfft_tpu.serve.store manifest``): load every listed artifact
        into the LRU so a replacement process compiles/loads everything
        BEFORE joining the pool. Returns the loaded signatures."""
        from .store import load_manifest
        payload = load_manifest(path)
        return self.warmup(payload.get("entries", ()), compile=compile,
                           strict=strict)

    def prewarm_signatures(self, signatures: Iterable[PlanSignature],
                           strict: bool = True) -> int:
        """Pull a signature set warm through the read-through tiers
        (LRU -> disk -> remote blob) BEFORE taking traffic — the
        joining-lane half of elastic pod membership: the incumbent
        frontend hands the joiner its live signature set and the joiner
        resolves every entry it can without building anything. Returns
        the count now resident. A signature no tier can answer raises
        :class:`~spfft_tpu.errors.PlanArtifactError` when ``strict``
        (a lane must not join the pod half-warm); distributed
        signatures the joiner derives locally (they are never
        serialized) are the caller's business and simply skip."""
        from ..errors import PlanArtifactError
        warmed = 0
        for sig in signatures:
            if self.get(sig) is not None:
                warmed += 1
                continue
            if strict and sig.device_count <= 1:
                raise PlanArtifactError(
                    f"prewarm cannot resolve {sig!r} from any artifact "
                    f"tier (see spfft_store_rejects_total / "
                    f"spfft_blob_ops_total for why)")
        return warmed

    # -- counters ----------------------------------------------------------
    @property
    def bytes_in_use(self) -> int:
        with self._lock:
            return self._bytes

    @property
    def hit_rate(self) -> float:
        """hits / (hits + misses) over the registry's lifetime; 0.0
        before any lookup."""
        with self._lock:
            total = self._hits + self._misses
            return self._hits / total if total else 0.0

    def stats(self) -> Dict[str, float]:
        """Counter snapshot for the metrics export."""
        with self._lock:
            total = self._hits + self._misses
            return {
                "plans": len(self._store),
                "bytes_in_use": self._bytes,
                "max_bytes": self._max_bytes,
                "max_plans": self._max_plans,
                "hits": self._hits,
                "misses": self._misses,
                "fast_hits": self._fast_hits,
                "evictions": self._evictions,
                "builds": self._builds,
                "build_failures": self._build_failures,
                "sig_memo_entries": sum(len(c) for c in
                                        self._sig_memo.values()),
                "sig_memo_bytes": self._sig_memo_bytes,
                "hit_rate": self._hits / total if total else 0.0,
                "store_hits": self._store_hits,
                "store_misses": self._store_misses,
                "store_spills": self._store_spills,
                "store_attached": self._disk is not None,
            }

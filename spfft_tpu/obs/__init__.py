"""spfft_tpu.obs — unified observability: tracing, counters, exporters.

The reproduction previously had three disjoint telemetry islands (the
``timing.py`` scope timer, ``serve.metrics`` counters, hand-rolled
bench JSON). This package unifies them behind one process-global
tracer + counter registry and two exporters, so a request can be
followed end-to-end (submit → queue-wait → bucket-formation → stage →
dispatch → device-execute → materialise → resolve) and a fleet scraper
or a human in Perfetto can consume the numbers without reading our
code.

* :mod:`~spfft_tpu.obs.trace` — :class:`Tracer` / :class:`Span` /
  :class:`RequestTrace`; off by default (``SPFFT_TPU_TRACE=1`` or
  :func:`enable`), sampled via ``SPFFT_TPU_TRACE_SAMPLE``, bounded
  ring buffer, zero-unclosed-spans lifecycle contract.
* :mod:`~spfft_tpu.obs.counters` — labelled counter/gauge registry
  (always on; a dict update per record).
* :mod:`~spfft_tpu.obs.exporters` — :func:`export_trace` (Chrome
  trace-event JSON for Perfetto / chrome://tracing) and
  :func:`prometheus_text` (text exposition over ServeMetrics,
  PlanRegistry, timing.GlobalTimer and the obs counters), plus the
  validating :func:`parse_prometheus_text`.
* :mod:`~spfft_tpu.obs.http` — :class:`MetricsServer`, the opt-in
  stdlib HTTP scrape endpoint (``/metrics`` Prometheus text,
  ``/healthz`` readiness JSON, ``/configz`` live knob values); enable
  via ``serve.bench --metrics-port`` or ``SPFFT_TPU_METRICS_PORT``.
* ``python -m spfft_tpu.obs`` — CLI: ``demo`` records a small traced
  serving run and writes both artifacts; ``validate`` structurally
  checks a trace JSON; ``prom`` prints/validates exposition text.

The recorder helpers below are the integration seams the rest of the
codebase calls (plan builds, registry builds, prewarms, distributed
exchange accounting, HLO collective counts). Counter recording is
always on; span recording only when tracing is enabled.

See docs/observability.md for the span taxonomy, exporter formats,
sampling knob and measured overhead.
"""

from __future__ import annotations

import time
from typing import Optional

from .counters import GLOBAL_COUNTERS, Counters
from .exporters import (export_trace, parse_prometheus_text,
                        prometheus_text, trace_events)
from .http import METRICS_PORT_ENV, MetricsServer, port_from_env
from .recorder import (BUNDLE_VERSION, EVENT_SPECS, GLOBAL_JOURNAL,
                       build_incident_bundle, capture_incident,
                       disable_recorder, enable_recorder, flag_trace,
                       maybe_auto_capture, merge_pod_bundle,
                       overhead_probe, record_event, recorder_active,
                       recorder_from_env, recorder_stats,
                       reset_recorder, retained_traces,
                       set_health_provider, set_incident_capturer,
                       set_latency_source, validate_bundle,
                       write_bundle)
from .trace import (GLOBAL_TRACER, RequestTrace, Span, TraceContext,
                    Tracer, active, disable, enable, span_context)

__all__ = [
    "Tracer", "Span", "RequestTrace", "GLOBAL_TRACER",
    "TraceContext", "span_context",
    "Counters", "GLOBAL_COUNTERS",
    "active", "enable", "disable",
    "export_trace", "trace_events", "prometheus_text",
    "parse_prometheus_text",
    "MetricsServer", "METRICS_PORT_ENV", "port_from_env",
    "record_compile", "record_plan_build", "record_exchange_plan",
    "record_hlo_counts", "record_plan_fallback", "record_store",
    "record_store_aot_skip",
    # flight recorder (obs.recorder)
    "EVENT_SPECS", "GLOBAL_JOURNAL", "BUNDLE_VERSION",
    "record_event", "enable_recorder", "disable_recorder",
    "recorder_active", "recorder_from_env", "recorder_stats",
    "reset_recorder", "flag_trace", "retained_traces",
    "build_incident_bundle", "capture_incident", "write_bundle",
    "maybe_auto_capture", "merge_pod_bundle", "validate_bundle",
    "set_health_provider", "set_incident_capturer",
    "set_latency_source", "overhead_probe",
]


def record_store(event: str, reason: Optional[str] = None) -> None:
    """One plan-artifact-store outcome (``hit`` / ``miss`` / ``spill``
    / ``evict`` / ``reject`` / ``manifest_refresh``; rejects carry
    their typed reason label). Counters always
    (``spfft_store_{hits,misses,spills,evictions,rejects,
    manifest_refreshes}_total``); a ``store`` instant on the compile
    track when tracing is on — next to the ``compile.store_load`` /
    ``compile.store_spill`` spans the store records, so Perfetto shows
    load-vs-build decisions inline with the compile timeline."""
    name = {"hit": "spfft_store_hits_total",
            "miss": "spfft_store_misses_total",
            "spill": "spfft_store_spills_total",
            "evict": "spfft_store_evictions_total",
            "reject": "spfft_store_rejects_total",
            "manifest_refresh":
                "spfft_store_manifest_refreshes_total"}[event]
    labels = {"reason": reason} if event == "reject" else {}
    GLOBAL_COUNTERS.inc(name, 1,
                        help="Plan-artifact store outcomes.", **labels)
    if active():
        args = {"event": event}
        if reason:
            args["reason"] = reason
        GLOBAL_TRACER.instant("store." + event, cat="compile",
                              track="compile", args=args)


def record_store_aot_skip(reason: str) -> None:
    """One non-fatal AOT executable skip (export or deserialize failed,
    platform mismatch) — the artifact/plan is fine, only the
    ahead-of-time executable is absent."""
    GLOBAL_COUNTERS.inc("spfft_store_aot_skipped_total", 1,
                        help="AOT executables skipped (non-fatal) by "
                             "reason.",
                        reason=reason)


def record_plan_fallback(stage: str, reason: str) -> None:
    """One plan-time Pallas fallback decision — a compression stage or
    a fused compression+DFT direction routed to the slower path, with
    why. Counter always (``spfft_plan_pallas_fallback_total`` by
    {stage, reason} — scrapeable fleet-wide via the /metrics endpoint),
    plus an instant span annotation on the compile track when tracing
    is on."""
    GLOBAL_COUNTERS.inc("spfft_plan_pallas_fallback_total", 1,
                        help="Plan-time Pallas fallback decisions by "
                             "stage and reason.",
                        stage=stage, reason=reason)
    if active():
        GLOBAL_TRACER.instant("plan.pallas_fallback", cat="compile",
                              track="compile",
                              args={"stage": stage, "reason": reason})


def record_compile(what: str, seconds: float, t0: Optional[float] = None,
                   **info) -> None:
    """One compile-ish event (registry build, prewarm, pin prewarm,
    batch-ladder compile): counters always, a ``compile`` track span
    when tracing is on. ``t0`` is the ``time.perf_counter()`` start
    when the caller measured a real interval; omitted, the span is
    recorded at now-minus-``seconds``."""
    GLOBAL_COUNTERS.inc("spfft_compile_events_total", 1,
                        help="Compile-path events by kind.", kind=what)
    GLOBAL_COUNTERS.inc("spfft_compile_seconds_total", seconds,
                        help="Compile-path seconds by kind.", kind=what)
    if active():
        t1 = (t0 + seconds) if t0 is not None else time.perf_counter()
        args = {k: v for k, v in info.items()
                if isinstance(v, (str, int, float, bool))}
        GLOBAL_TRACER.complete(f"compile.{what}", t1 - seconds, t1,
                               cat="compile", track="compile",
                               args=args or None)


def record_plan_build(plan, seconds: float,
                      t0: Optional[float] = None) -> None:
    """Called by ``TransformPlan.__init__`` (kind=local) and the
    distributed plan (kind=distributed) with the measured construction
    time."""
    kind = ("distributed" if hasattr(plan, "dist_plan") else "local")
    GLOBAL_COUNTERS.inc("spfft_plan_builds_total", 1,
                        help="Transform plans constructed.", kind=kind)
    GLOBAL_COUNTERS.inc("spfft_plan_build_seconds_total", seconds,
                        help="Seconds spent constructing plans.",
                        kind=kind)
    if active():
        t1 = (t0 + seconds) if t0 is not None else time.perf_counter()
        try:
            args = {"kind": kind, "precision": plan.precision,
                    "dims": f"{plan.dim_x}x{plan.dim_y}x{plan.dim_z}"}
        except Exception:
            args = {"kind": kind}
        GLOBAL_TRACER.complete("compile.plan_build", t1 - seconds, t1,
                               cat="compile", track="compile", args=args)


def record_exchange_plan(plan, seconds: float,
                         t0: Optional[float] = None) -> None:
    """Surface a ``DistributedTransformPlan``'s exact exchange
    accounting — total/busiest-link wire bytes and, when the overlap
    pipeline is active, the per-chunk split from ``OverlapSchedule`` —
    as counters plus (when tracing) an ``exchange`` track span and a
    per-chunk counter series. Called at plan construction; distributed
    rounds stop hand-rolling these numbers into bench JSON."""
    labels = {"exchange": plan.exchange.value,
              "shards": str(plan.dist_plan.num_shards),
              "chunks": str(plan.overlap_chunks)}
    wire = int(plan.exchange_wire_bytes())
    busiest = int(plan.exchange_busiest_link_bytes())
    GLOBAL_COUNTERS.inc("spfft_exchange_plans_total", 1,
                        help="Distributed plans constructed.", **labels)
    GLOBAL_COUNTERS.set("spfft_exchange_wire_bytes", wire,
                        help="Exact off-shard bytes per exchange of the "
                             "most recent plan.", **labels)
    GLOBAL_COUNTERS.set("spfft_exchange_busiest_link_bytes", busiest,
                        help="Bottleneck-link bytes per exchange of the "
                             "most recent plan.", **labels)
    GLOBAL_COUNTERS.set("spfft_wire_rung",
                        float(getattr(plan, "wire_rung", 0)),
                        help="Resolved wire-compression rung of the most "
                             "recent distributed plan (0=full, 1=f32, "
                             "2=bf16, 3=int8).", **labels)
    if not active():
        return
    ov = getattr(plan, "_overlap", None)
    per_chunk = []
    if ov is not None:
        elem = plan._wire_elem_bytes()
        # int8 rung: each chunk also carries its scale sidecar — one f32
        # per (slot, quant row) over the chunk's stick/plane slice
        int8 = getattr(plan, "wire_rung", 0) == 3
        dp = plan.dist_plan
        links = dp.num_shards * (dp.num_shards - 1)
        for c in range(ov.num_chunks):
            sc_b = (links * ov.chunk_scale_rows(c) * 4) if int8 else 0
            sc_f = (links * ov.chunk_scale_rows(c, forward=True) * 4
                    ) if int8 else 0
            per_chunk.append({
                "bwd_bytes": ov.chunk_wire_elements(c) * elem + sc_b,
                "fwd_bytes": ov.chunk_wire_elements(c, forward=True)
                * elem + sc_f,
                "busiest_link_bytes":
                    ov.chunk_busiest_link_elements(c) * elem,
            })
            GLOBAL_TRACER.counter(
                "exchange.chunk_wire_bytes",
                {"bwd": per_chunk[-1]["bwd_bytes"],
                 "fwd": per_chunk[-1]["fwd_bytes"]},
                cat="exchange", track="exchange")
    t1 = (t0 + seconds) if t0 is not None else time.perf_counter()
    args = dict(labels)
    args.update({"wire_bytes": wire, "busiest_link_bytes": busiest})
    if per_chunk:
        args["per_chunk"] = per_chunk
    GLOBAL_TRACER.complete("exchange.plan_build", t1 - seconds, t1,
                           cat="exchange", track="exchange", args=args)


def record_hlo_counts(label: str, lowered_text: Optional[str] = None,
                      compiled_text: Optional[str] = None) -> dict:
    """Surface ``utils.hlo_inspect`` collective counts (lowered
    StableHLO) and async start/done split evidence (compiled HLO) as
    metrics + an instant event. Returns the recorded dict."""
    from ..utils.hlo_inspect import collective_async_split, \
        count_collectives
    out: dict = {"label": label}
    if lowered_text is not None:
        counts = count_collectives(lowered_text)
        out["collectives"] = counts
        for op, n in counts.items():
            if n:
                GLOBAL_COUNTERS.set(
                    "spfft_hlo_collectives", n,
                    help="Collective launches in the most recently "
                         "inspected lowered module.",
                    label=label, op=op)
    if compiled_text is not None:
        split = collective_async_split(compiled_text)
        out["async_split"] = split
        GLOBAL_COUNTERS.set("spfft_hlo_async_starts", split["starts"],
                            help="Async collective starts in the most "
                                 "recently inspected compiled module.",
                            label=label)
        GLOBAL_COUNTERS.set("spfft_hlo_async_dones", split["dones"],
                            help="Async collective dones in the most "
                                 "recently inspected compiled module.",
                            label=label)
    if active():
        args = {"label": label}
        if "collectives" in out:
            args.update({f"collectives_{k}": v
                         for k, v in out["collectives"].items() if v})
        if "async_split" in out:
            args["async_starts"] = out["async_split"]["starts"]
            args["async_dones"] = out["async_split"]["dones"]
        GLOBAL_TRACER.instant("exchange.hlo_counts", cat="exchange",
                              track="exchange", args=args)
    return out

"""Observability CLI: ``python -m spfft_tpu.obs``.

Three subcommands:

* ``demo`` — record a small fully-traced serving run (registry build,
  deterministic request waves through a ``ServeExecutor``, plus a
  distributed-plan exchange when >= 2 devices are visible) and write
  the Chrome trace JSON / Prometheus text artifacts. The zero-to-trace
  path for someone who has never read this codebase:
  ``python -m spfft_tpu.obs demo --trace-out /tmp/spfft.trace.json``
  then open the file in https://ui.perfetto.dev.
* ``validate FILE`` — structural validation of an exported trace JSON
  (parses, non-empty, well-formed events, zero open spans recorded);
  ``--require-stage NAME`` (repeatable) additionally demands named
  spans. Exit 1 on any violation — the ``make trace-smoke`` backstop.
* ``prom [FILE]`` — with a FILE, round-trip it through the validating
  exposition-format parser; without, print the current process's
  :func:`~spfft_tpu.obs.exporters.prometheus_text`.
* ``incident`` — flight-recorder ops verb: ``--validate FILE``
  schema-checks a captured bundle; otherwise capture one NOW from
  this process (``--dir`` overrides the incident directory) and, with
  repeatable ``--peer [name=]ip:port`` agent addresses, gather every
  peer's bundle over the wire into one pod bundle — the out-of-band
  collection path when no pod frontend is running.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List

from . import (GLOBAL_TRACER, enable, export_trace, parse_prometheus_text,
               prometheus_text, record_hlo_counts)

#: The eight per-request pipeline stages every end-to-end trace covers.
REQUEST_STAGES = ("serve.submit", "serve.queue_wait",
                  "serve.bucket_formation", "serve.stage",
                  "serve.dispatch", "serve.device_execute",
                  "serve.materialise", "serve.resolve")


def validate_trace_payload(payload: dict,
                           require_names=()) -> List[str]:
    """Structural checks over an exported Chrome trace payload; returns
    a list of failure messages (empty = valid)."""
    failures: List[str] = []
    events = payload.get("traceEvents")
    if not isinstance(events, list) or not events:
        return ["traceEvents missing or empty"]
    tracks = {}
    names = set()
    for i, ev in enumerate(events):
        ph = ev.get("ph")
        if ph not in ("X", "i", "C", "M"):
            failures.append(f"event {i}: unknown ph {ph!r}")
            continue
        if ph == "M":
            if ev.get("name") == "thread_name":
                tracks.setdefault(ev.get("tid"),
                                  {"name": ev["args"]["name"],
                                   "events": 0})
            continue
        if not isinstance(ev.get("name"), str) or "ts" not in ev:
            failures.append(f"event {i}: missing name/ts")
            continue
        names.add(ev["name"])
        if ev.get("tid") in tracks:
            tracks[ev["tid"]]["events"] += 1
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                failures.append(
                    f"event {i} ({ev['name']}): bad dur {dur!r}")
    for tid, info in tracks.items():
        if info["events"] == 0:
            failures.append(
                f"track {info['name']!r} (tid {tid}) declared but "
                f"empty")
    for name in require_names:
        if name not in names:
            failures.append(f"required span {name!r} missing from trace")
    stats = (payload.get("otherData") or {}).get("tracer") or {}
    if stats.get("open", 0):
        failures.append(f"tracer recorded {stats['open']} unclosed "
                        f"spans at export time")
    return failures


def _cmd_demo(args) -> int:
    if args.cpu or args.devices > 1:
        from ..utils.platform import force_virtual_cpu_devices
        force_virtual_cpu_devices(max(args.devices, 2 if args.cpu else 1))
    enable()
    GLOBAL_TRACER.reset()

    import numpy as np

    import jax

    from ..benchmark import cutoff_stick_triplets
    from ..serve.executor import ServeExecutor
    from ..serve.registry import PlanRegistry
    from ..types import TransformType

    n = args.dim
    triplets = cutoff_stick_triplets(n, n, n, 0.9, hermitian=False)
    registry = PlanRegistry()
    sig, plan = registry.get_or_build(TransformType.C2C, n, n, n,
                                      triplets)
    nv = plan.index_plan.num_values
    rng = np.random.default_rng(0)
    ex = ServeExecutor(registry, autostart=False, batch_window=0.0)
    waves, wave = max(1, args.requests // 4), 4
    for _ in range(waves):
        futures = [ex.submit(
            sig, rng.standard_normal((nv, 2)).astype(np.float32))
            for _ in range(wave)]
        ex._drain_once()
        for f in futures:
            f.result(timeout=60)
    snap = ex.metrics
    # distributed exchange accounting (needs a >= 2 device mesh)
    if len(jax.devices()) >= 2:
        from ..parallel import make_distributed_plan, make_mesh
        from ..utils.workloads import (even_plane_split,
                                       round_robin_stick_partition)
        S = 2
        parts = round_robin_stick_partition(triplets, (n, n, n), S)
        planes = even_plane_split(n, S)
        dplan = make_distributed_plan(TransformType.C2C, n, n, n, parts,
                                      planes, mesh=make_mesh(S),
                                      overlap_chunks=2)
        vals = [np.zeros(len(p), np.complex64) for p in parts]
        v = dplan.shard_values(vals)
        lowered = dplan._backward_jit.lower(v, *dplan._device_tables)
        record_hlo_counts("obs-demo", lowered.as_text())
    ex.close()
    open_spans = GLOBAL_TRACER.open_count()
    if args.trace_out:
        payload = export_trace(args.trace_out)
        failures = validate_trace_payload(payload,
                                          require_names=REQUEST_STAGES)
        print(f"wrote {args.trace_out} "
              f"({len(payload['traceEvents'])} events) — open it in "
              f"https://ui.perfetto.dev")
        for msg in failures:
            print(f"FAIL: {msg}", file=sys.stderr)
        if failures:
            return 1
    text = prometheus_text(metrics=snap, registry=registry)
    parse_prometheus_text(text)  # self-check
    if args.prom_out:
        with open(args.prom_out, "w") as f:
            f.write(text)
        print(f"wrote {args.prom_out} ({len(text.splitlines())} lines)")
    elif not args.trace_out:
        print(text, end="")
    if open_spans:
        print(f"FAIL: {open_spans} spans left open", file=sys.stderr)
        return 1
    return 0


def _cmd_validate(args) -> int:
    with open(args.file) as f:
        try:
            payload = json.load(f)
        except json.JSONDecodeError as exc:
            print(f"FAIL: {args.file} is not JSON: {exc}",
                  file=sys.stderr)
            return 1
    require = list(args.require_stage or [])
    if args.require_request_stages:
        require.extend(REQUEST_STAGES)
    failures = validate_trace_payload(payload, require_names=require)
    for msg in failures:
        print(f"FAIL: {msg}", file=sys.stderr)
    if not failures:
        events = payload.get("traceEvents", [])
        print(f"ok: {args.file} ({len(events)} events)")
    return 1 if failures else 0


def _cmd_prom(args) -> int:
    if args.file:
        with open(args.file) as f:
            text = f.read()
        try:
            series = parse_prometheus_text(text)
        except ValueError as exc:
            print(f"FAIL: {args.file}: {exc}", file=sys.stderr)
            return 1
        print(f"ok: {args.file} ({len(series)} series)")
        return 0
    print(prometheus_text(), end="")
    return 0


def _cmd_incident(args) -> int:
    from . import recorder
    if args.validate:
        try:
            with open(args.validate) as f:
                bundle = json.load(f)
        except (OSError, json.JSONDecodeError) as exc:
            print(f"FAIL: {args.validate}: {exc}", file=sys.stderr)
            return 1
        failures = recorder.validate_bundle(bundle)
        for msg in failures:
            print(f"FAIL: {msg}", file=sys.stderr)
        if failures:
            return 1
        hosts = sorted(bundle.get("hosts") or ())
        detail = f", hosts: {', '.join(hosts)}" if hosts else ""
        print(f"ok: {args.validate} ({bundle.get('kind')} bundle, "
              f"{len(bundle.get('timeline') or bundle.get('events') or ())}"
              f" events{detail})")
        return 0
    if not recorder.recorder_active():
        recorder.enable_recorder(incident_dir=args.dir, auto=False)
    reason = args.reason
    if args.peer:
        from ..net.transport import TcpHostLane
        bundles = {args.host: recorder.build_incident_bundle(
            reason, host=args.host)}
        for spec in args.peer:
            name, _, addr = spec.rpartition("=")
            ip, _, port = addr.rpartition(":")
            name = name or addr
            try:
                lane = TcpHostLane(name, (ip or "127.0.0.1", int(port)))
            except (OSError, ValueError) as exc:
                bundles[name] = {"error": f"{type(exc).__name__}: {exc}"}
                continue
            try:
                bundles[name] = lane.rpc_incident(reason)
            except Exception as exc:
                bundles[name] = {"error": f"{type(exc).__name__}: {exc}"}
            finally:
                close = getattr(lane, "close", None)
                if close is not None:
                    close()
        pod = recorder.merge_pod_bundle(reason, bundles)
        try:
            path = recorder.write_bundle(pod, directory=args.dir)
        except Exception as exc:
            print(f"FAIL: bundle write failed: {exc}", file=sys.stderr)
            return 1
    else:
        path = recorder.capture_incident(reason, directory=args.dir)
        if path is None:
            print("FAIL: incident capture failed (no incident dir? "
                  "pass --dir)", file=sys.stderr)
            return 1
    with open(path) as f:
        failures = recorder.validate_bundle(json.load(f))
    for msg in failures:
        print(f"FAIL: {msg}", file=sys.stderr)
    print(f"wrote {path}")
    return 1 if failures else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m spfft_tpu.obs",
        description="spfft_tpu observability: trace/metrics exporters")
    sub = p.add_subparsers(dest="cmd", required=True)

    demo = sub.add_parser("demo", help="record a small traced serving "
                                       "run and export artifacts")
    demo.add_argument("--dim", type=int, default=12)
    demo.add_argument("--requests", type=int, default=16)
    demo.add_argument("--trace-out", default=None, metavar="FILE.json")
    demo.add_argument("--prom-out", default=None, metavar="FILE.prom")
    demo.add_argument("--cpu", action="store_true",
                      help="force a virtual >= 2-device CPU platform "
                           "(so the exchange demo runs)")
    demo.add_argument("--devices", type=int, default=0)

    val = sub.add_parser("validate",
                         help="structurally validate a trace JSON")
    val.add_argument("file")
    val.add_argument("--require-stage", action="append", default=[])
    val.add_argument("--require-request-stages", action="store_true",
                     help="demand all eight per-request pipeline "
                          "stages")

    prom = sub.add_parser("prom", help="print (or validate) Prometheus "
                                       "exposition text")
    prom.add_argument("file", nargs="?", default=None)

    inc = sub.add_parser("incident",
                         help="capture or validate a flight-recorder "
                              "incident bundle")
    inc.add_argument("--validate", default=None, metavar="FILE.json",
                     help="schema-check a captured bundle instead of "
                          "capturing")
    inc.add_argument("--dir", default=None,
                     help="incident directory (default: "
                          "SPFFT_TPU_INCIDENT_DIR)")
    inc.add_argument("--reason", default="cli")
    inc.add_argument("--host", default="local",
                     help="host label for this process's bundle")
    inc.add_argument("--peer", action="append", default=[],
                     metavar="[NAME=]IP:PORT",
                     help="agent address to gather into a pod bundle "
                          "(repeatable)")

    args = p.parse_args(argv if argv is not None else sys.argv[1:])
    if args.cmd == "demo":
        return _cmd_demo(args)
    if args.cmd == "validate":
        return _cmd_validate(args)
    if args.cmd == "incident":
        return _cmd_incident(args)
    return _cmd_prom(args)


if __name__ == "__main__":
    sys.exit(main())

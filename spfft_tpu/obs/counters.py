"""Labelled counter/gauge registry for the Prometheus export.

The compile and exchange observability the ISSUE names (plan-build and
jit-compile durations, per-plan wire/busiest-link bytes, HLO collective
counts) are process-wide facts, not per-executor ones — they need a
sink that exists before any server object does and that costs ~a dict
update when tracing is off. This is that sink: metric names follow the
Prometheus data model (``spfft_*``, ``_total`` suffix on counters), the
exporter (:func:`spfft_tpu.obs.exporters.prometheus_text`) renders it
verbatim, and everything else in the process (plan.py, registry,
executor, dist.py) records into the one :data:`GLOBAL_COUNTERS`.

Counters only go up (``inc``); gauges hold the last written value
(``set``). Labels are passed as kwargs and become Prometheus labels.
"""

from __future__ import annotations

import re
import threading
from typing import Dict, Optional, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: THE series registry: every ``spfft_*`` counter/gauge any part of the
#: process emits — through :data:`GLOBAL_COUNTERS` or synthesised by
#: ``obs.exporters.prometheus_text``'s ServeMetrics/registry/timing
#: families — declared exactly once, as ``name: (type, help)``. The
#: static counter-registry checker (``python -m spfft_tpu.analysis``)
#: fails the build on a recorded series missing here (a typo'd name
#: would otherwise become a silently-new series) and on a declared
#: series nothing records or renders; at runtime :class:`Counters`
#: enforces the declared type and defaults the help text from here.
METRIC_SPECS: Dict[str, Tuple[str, str]] = {
    # compile / plan observability (obs.record_* helpers)
    "spfft_compile_events_total":
        ("counter", "Compile-path events by kind."),
    "spfft_compile_seconds_total":
        ("counter", "Compile-path seconds by kind."),
    "spfft_plan_builds_total":
        ("counter", "Transform plans constructed."),
    "spfft_plan_build_seconds_total":
        ("counter", "Seconds spent constructing plans."),
    "spfft_plan_pallas_fallback_total":
        ("counter",
         "Plan-time Pallas fallback decisions by stage and reason. "
         "Stages: decompress, compress, fused_decompress_zdft, "
         "fused_zdft_compress, dist_fused_decompress_zdft, "
         "dist_fused_zdft_compress."),
    # distributed exchange accounting
    "spfft_exchange_plans_total":
        ("counter", "Distributed plans constructed."),
    "spfft_exchange_wire_bytes":
        ("gauge",
         "Exact off-shard bytes per exchange of the most recent plan."),
    "spfft_exchange_busiest_link_bytes":
        ("gauge",
         "Bottleneck-link bytes per exchange of the most recent plan."),
    "spfft_wire_rung":
        ("gauge",
         "Resolved wire-compression rung of the most recent distributed "
         "plan (0=full, 1=f32, 2=bf16, 3=int8)."),
    "spfft_wire_rung_changes_total":
        ("counter",
         "Controller wire-rung moves by direction (up=escalate under "
         "exposed exchange, down=decay)."),
    "spfft_wire_rung_declined_total":
        ("counter",
         "Wire rungs refused at plan build by reason (over_budget, "
         "exact_count_layout, fault_injected)."),
    "spfft_hlo_collectives":
        ("gauge", "Collective launches in the most recently inspected "
                  "lowered module."),
    "spfft_hlo_async_starts":
        ("gauge", "Async collective starts in the most recently "
                  "inspected compiled module."),
    "spfft_hlo_async_dones":
        ("gauge", "Async collective dones in the most recently "
                  "inspected compiled module."),
    # plan-artifact store
    "spfft_store_hits_total":
        ("counter", "Plan-artifact store outcomes: warm loads."),
    "spfft_store_misses_total":
        ("counter", "Plan-artifact store outcomes: misses."),
    "spfft_store_spills_total":
        ("counter", "Plan-artifact store outcomes: write-behind "
                    "spills."),
    "spfft_store_evictions_total":
        ("counter", "Plan-artifact store outcomes: GC evictions."),
    "spfft_store_rejects_total":
        ("counter", "Plan-artifact store outcomes: typed artifact "
                    "rejections by reason."),
    "spfft_store_manifest_refreshes_total":
        ("counter", "Plan-artifact store outcomes: live boot-prewarm "
                    "manifest merges on spill."),
    "spfft_store_aot_skipped_total":
        ("counter", "AOT executables skipped (non-fatal) by reason."),
    # control plane
    "spfft_control_decisions_total":
        ("counter", "Accepted control-plane knob changes."),
    "spfft_control_knob":
        ("gauge", "Current value of each control-plane knob."),
    "spfft_control_clamped_total":
        ("counter", "Knob writes clamped into their declared bounds."),
    "spfft_control_steps_total":
        ("counter", "Feedback-controller evaluation steps."),
    "spfft_control_step_errors_total":
        ("counter", "Feedback-controller steps that raised."),
    # SLO watchdog
    "spfft_slo_evaluations_total":
        ("counter", "SLO watchdog evaluations."),
    "spfft_slo_objective":
        ("gauge", "Declared SLO objective value."),
    "spfft_slo_observed":
        ("gauge", "Observed value at last SLO evaluation."),
    "spfft_slo_burn_rate":
        ("gauge", "observed/objective at last evaluation (-1 = "
                  "infinite: a zero objective was burned)."),
    "spfft_slo_violation":
        ("gauge", "1 while this SLO's burn rate exceeds its budget."),
    "spfft_slo_violations_total":
        ("counter", "SLO violations observed across evaluations."),
    "spfft_slo_window_burn_rate":
        ("gauge", "Mean burn rate over each alerting window "
                  "(labels: slo, window=fast|slow; -1 = infinite)."),
    "spfft_slo_window_alert":
        ("gauge", "1 while BOTH burn windows of this SLO exceed the "
                  "budget (multi-window page condition)."),
    "spfft_slo_window_alerts_total":
        ("counter", "Multi-window page conditions entered."),
    # pod frontend (serve.cluster)
    "spfft_cluster_hosts":
        ("gauge", "Pod frontend host lanes, labelled by lane state."),
    "spfft_cluster_health":
        ("gauge", "Pod aggregate health state (one-hot; worst lane "
                  "health wins)."),
    "spfft_cluster_routed_total":
        ("counter", "Requests routed by the pod frontend, labelled "
                    "{host, kind=single|distributed}."),
    "spfft_cluster_rpcs_total":
        ("counter", "Host-lane RPCs issued by the pod frontend, "
                    "labelled {host, op}."),
    "spfft_cluster_rpc_failures_total":
        ("counter", "Host-lane RPCs that failed, labelled {host, op}."),
    "spfft_cluster_reconciliations_total":
        ("counter", "Pod plan reconciliations, labelled by outcome "
                    "(ok|mismatch|failed)."),
    "spfft_cluster_spmd_requests_total":
        ("counter", "Distributed-plan requests executed on the "
                    "pod-wide SPMD lane."),
    "spfft_cluster_spmd_coalesced_total":
        ("counter", "Distributed requests that shared a coalesced SPMD "
                    "window round (batch >= 2) — one collective round "
                    "moved all of them."),
    "spfft_cluster_spmd_batch_size_total":
        ("counter", "Coalesced SPMD rounds by batch size, labelled "
                    "{size} (the coalescer's batch-size histogram)."),
    "spfft_cluster_lane_deaths_total":
        ("counter", "Host lanes marked dead by the pod frontend, "
                    "labelled by host."),
    # serving families (rendered by exporters._serve_families from a
    # ServeMetrics snapshot)
    "spfft_serve_completed_total":
        ("counter", "Requests completed successfully."),
    "spfft_serve_failed_total":
        ("counter", "Requests resolved with an error."),
    "spfft_serve_rejected_queue_full_total":
        ("counter", "Submits rejected by backpressure."),
    "spfft_serve_expired_deadline_total":
        ("counter", "Requests expired before dispatch."),
    "spfft_serve_fused_batches_total":
        ("counter", "Buckets dispatched through the fused path."),
    "spfft_serve_serial_batches_total":
        ("counter", "Buckets dispatched serially."),
    "spfft_serve_padded_rows_total":
        ("counter", "Ladder pad rows dispatched."),
    "spfft_serve_pinned_batches_total":
        ("counter", "Buckets dispatched at a pinned shape."),
    "spfft_serve_fused_rows_total":
        ("counter", "Live rows dispatched through fused buckets."),
    "spfft_serve_completed_by_class_total":
        ("counter", "Completions per priority class."),
    "spfft_serve_queue_depth":
        ("gauge", "Request queue depth at last enqueue/dequeue."),
    "spfft_serve_max_queue_depth":
        ("gauge", "High-water queue depth."),
    "spfft_serve_latency_seconds":
        ("gauge",
         "Request latency percentiles over the bounded reservoir."),
    "spfft_serve_queue_wait_seconds":
        ("gauge", "Enqueue->dispatch wait percentiles (recent window) "
                  "— the controller's queue-pressure signal."),
    "spfft_serve_device_execute_seconds":
        ("gauge", "Dispatch->materialised bucket time percentiles "
                  "(recent window) — the controller's device-cost "
                  "signal."),
    "spfft_serve_latency_by_class_seconds":
        ("gauge", "Per-priority-class latency percentiles."),
    "spfft_serve_batch_size_total":
        ("counter", "Dispatched buckets by live-row count and path."),
    "spfft_serve_overhead_seconds_total":
        ("counter", "Host-side orchestration seconds."),
    "spfft_serve_health":
        ("gauge", "Executor lifecycle state (one-hot)."),
    # serving failure-handling families (the ServeMetrics.health()
    # numeric counters, rendered as spfft_serve_<key>_total)
    "spfft_serve_retries_total":
        ("counter", "Failure-handling counter: retries."),
    "spfft_serve_retries_exhausted_total":
        ("counter", "Failure-handling counter: retries_exhausted."),
    "spfft_serve_retries_by_class_total":
        ("counter", "Failure-handling counter: retries_by_class."),
    "spfft_serve_retries_exhausted_by_class_total":
        ("counter",
         "Failure-handling counter: retries_exhausted_by_class."),
    "spfft_serve_bucket_fallbacks_total":
        ("counter", "Failure-handling counter: bucket_fallbacks."),
    "spfft_serve_quarantines_total":
        ("counter", "Failure-handling counter: quarantines."),
    "spfft_serve_probations_total":
        ("counter", "Failure-handling counter: probations."),
    "spfft_serve_readmissions_total":
        ("counter", "Failure-handling counter: readmissions."),
    "spfft_serve_no_healthy_device_total":
        ("counter", "Failure-handling counter: no_healthy_device."),
    "spfft_serve_dispatcher_crashes_total":
        ("counter", "Failure-handling counter: dispatcher_crashes."),
    "spfft_serve_dispatcher_restarts_total":
        ("counter", "Failure-handling counter: dispatcher_restarts."),
    "spfft_serve_pin_prewarms_total":
        ("counter", "Failure-handling counter: pin_prewarms."),
    "spfft_serve_purged_expired_total":
        ("counter", "Failure-handling counter: purged_expired."),
    "spfft_serve_request_attributed_failures_total":
        ("counter",
         "Failure-handling counter: request_attributed_failures."),
    # plan-registry families (exporters._registry_families over
    # PlanRegistry.stats())
    "spfft_registry_plans": ("gauge", "Plan registry plans."),
    "spfft_registry_bytes_in_use":
        ("gauge", "Plan registry bytes in use."),
    "spfft_registry_max_bytes": ("gauge", "Plan registry max bytes."),
    "spfft_registry_max_plans": ("gauge", "Plan registry max plans."),
    "spfft_registry_sig_memo_entries":
        ("gauge", "Plan registry sig memo entries."),
    "spfft_registry_sig_memo_bytes":
        ("gauge", "Plan registry sig memo bytes."),
    "spfft_registry_hit_rate": ("gauge", "Plan registry hit rate."),
    "spfft_registry_store_attached":
        ("gauge", "Plan registry store attached."),
    "spfft_registry_hits_total": ("counter", "Plan registry hits."),
    "spfft_registry_misses_total":
        ("counter", "Plan registry misses."),
    "spfft_registry_fast_hits_total":
        ("counter", "Plan registry fast hits."),
    "spfft_registry_evictions_total":
        ("counter", "Plan registry evictions."),
    "spfft_registry_builds_total":
        ("counter", "Plan registry builds."),
    "spfft_registry_build_failures_total":
        ("counter", "Plan registry build failures."),
    "spfft_registry_store_hits_total":
        ("counter", "Plan registry store hits."),
    "spfft_registry_store_misses_total":
        ("counter", "Plan registry store misses."),
    "spfft_registry_store_spills_total":
        ("counter", "Plan registry store spills."),
    # timing + tracer lifecycle families
    "spfft_timing_seconds_total":
        ("counter",
         "Accumulated scope-timer seconds (timing.GlobalTimer)."),
    "spfft_timing_calls_total":
        ("counter", "Scope-timer call counts (timing.GlobalTimer)."),
    "spfft_trace_spans_started_total":
        ("counter", "Spans begun since the tracer's last reset."),
    "spfft_trace_spans_closed_total":
        ("counter", "Spans finished since the tracer's last reset."),
    "spfft_trace_spans_open":
        ("gauge", "Spans currently open (must be 0 at quiescence)."),
    "spfft_trace_events_dropped_total":
        ("counter", "Events dropped by the bounded ring buffer."),
    # flight recorder (obs.recorder): journal, tail retention, bundles
    "spfft_recorder_events_total":
        ("counter",
         "Typed events appended to the flight-recorder journal, "
         "labelled {kind} (every kind declared in EVENT_SPECS)."),
    "spfft_recorder_events_dropped_total":
        ("counter",
         "Journal events dropped (undeclared kind — the analyzer's "
         "event-registry checker catches these statically too)."),
    "spfft_recorder_traces_retained_total":
        ("counter",
         "Completed traces promoted to the retained ring, labelled "
         "{reason=error|slow|flagged}."),
    "spfft_recorder_incidents_total":
        ("counter",
         "Incident bundles captured successfully, labelled {trigger} "
         "(the reason prefix: slo_alert, health_degraded, "
         "health_failed, lane_death, manual, ...)."),
    "spfft_recorder_incident_failures_total":
        ("counter",
         "Incident bundle captures that failed non-fatally (the "
         "obs.capture fault site fires here in chaos storms)."),
    # package-wide fault seam (spfft_tpu.faults) + degradation ladders
    "spfft_faults_injected_total":
        ("counter",
         "Faults fired by a FaultPlan, labelled {site, kind}."),
    "spfft_faults_armed":
        ("gauge", "1 while an ambient fault plan is armed."),
    "spfft_fused_demotions_total":
        ("counter",
         "Runtime fused-kernel demotions to the unfused composition, "
         "labelled by plan direction (which=dec|cmp)."),
    "spfft_fused_reprobes_total":
        ("counter",
         "Fused-path re-probe attempts after a runtime demotion, "
         "labelled {which, outcome=readmitted|failed}."),
    "spfft_store_degraded":
        ("gauge",
         "1 while the plan-artifact store is in memory-only "
         "degradation (persistent disk fault; spills disabled)."),
    "spfft_store_io_retries_total":
        ("counter",
         "Transient store I/O errors absorbed by the bounded "
         "retry-with-backoff rung, labelled by op."),
    "spfft_store_reprobes_total":
        ("counter",
         "Degraded-store disk re-probe attempts, labelled "
         "{outcome=recovered|failed}."),
    "spfft_execute_timeouts_total":
        ("counter",
         "Bucket materialisations that exceeded execute_timeout_ms "
         "and were failed as typed transient ExecuteTimeoutError."),
    # wire transport + elastic membership + remote artifact tier (net/)
    "spfft_cluster_membership_total":
        ("counter",
         "Pod membership transitions, labelled {event="
         "join_started|prewarmed|reconciled|joined|join_failed|"
         "leave_started|drained|left|evicted|readmitted}."),
    "spfft_cluster_spmd_rejected_total":
        ("counter",
         "SPMD-lane submissions refused by admission control, "
         "labelled {reason=queue_full|expired}."),
    "spfft_net_frames_total":
        ("counter", "Wire frames moved, labelled {dir=send|recv}."),
    "spfft_net_bytes_total":
        ("counter",
         "Wire bytes moved (preamble+header+payload), labelled "
         "{dir=send|recv}."),
    "spfft_net_rpc_rtt_seconds":
        ("gauge",
         "EWMA round-trip latency of each host lane's wire RPCs — "
         "the third load_score term, labelled {host}."),
    "spfft_net_agent_requests_total":
        ("counter", "Requests a HostAgent served, labelled {op}."),
    "spfft_net_agent_rejected_total":
        ("counter",
         "Submits a HostAgent refused at its own admission seam, "
         "labelled {reason=queue_full|expired|auth|stale_epoch}."),
    "spfft_blob_ops_total":
        ("counter",
         "Remote blob-tier operations, labelled {op=get|put, "
         "outcome=hit|miss|ok|error}."),
    "spfft_store_remote_total":
        ("counter",
         "Plan-artifact store remote-tier outcomes, labelled "
         "{op=get|put, outcome=hit|miss|ok|error}."),
    # lease-based membership + lane resurrection (round 21)
    "spfft_net_rpc_retries_total":
        ("counter",
         "Wire-RPC connect retries before a lane was declared dead "
         "(bounded backoff in the sync connect path), labelled "
         "{verb}."),
    "spfft_membership_epoch":
        ("gauge",
         "Current membership-view epoch as each node last saw it, "
         "labelled {node} (coordinator host or frontend id) — nodes "
         "converging is the split-brain invariant."),
    "spfft_membership_transitions_total":
        ("counter",
         "Lease-ladder state transitions at the view coordinator, "
         "labelled {host, to=alive|suspected|probed|evicted}."),
    "spfft_membership_heartbeats_total":
        ("counter",
         "Membership lease-renewal heartbeats, labelled "
         "{outcome=ok|redirect|failed}."),
    "spfft_membership_views_total":
        ("counter",
         "Signed membership-view traffic, labelled "
         "{outcome=served|adopted|stale|bad_sig|error}."),
    "spfft_cluster_stale_epoch_total":
        ("counter",
         "Operations rejected for carrying a stale view epoch "
         "(typed transient StaleEpochError; the sender refetches the "
         "view and retries), labelled {node}."),
    "spfft_cluster_probes_total":
        ("counter",
         "Health probes of dead lanes by the resurrection ladder, "
         "labelled {host, outcome=ok|failed}."),
    "spfft_cluster_readmits_total":
        ("counter",
         "Dead-lane readmission attempts after a successful probe, "
         "labelled {host, outcome=readmitted|blocked}."),
    "spfft_blob_gc_total":
        ("counter",
         "Remote blob-tier gc sweep outcomes over the req/ journal "
         "namespace, labelled {outcome=removed|error|skipped}."),
}


class Counters:
    """Thread-safe registry of named counter/gauge families."""

    def __init__(self):
        self._lock = threading.Lock()
        # name -> {"type": "counter"|"gauge", "help": str,
        #          "samples": {(("k","v"), ...): float}}
        self._metrics: Dict[str, dict] = {}  #: guarded by _lock

    # lock: holds(_lock)
    def _family(self, name: str, mtype: str, help_: Optional[str]):
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r}")
        spec = METRIC_SPECS.get(name)
        if spec is not None:
            # the declared registry is authoritative: a recorder that
            # disagrees with the declared type is the same bug the
            # static counter-registry checker catches, enforced live
            if spec[0] != mtype:
                raise ValueError(
                    f"metric {name!r} is declared a {spec[0]} in "
                    f"METRIC_SPECS but recorded as a {mtype}")
            if help_ is None:
                help_ = spec[1]
        fam = self._metrics.get(name)
        if fam is None:
            fam = self._metrics[name] = {
                "type": mtype, "help": help_ or name, "samples": {}}
        elif fam["type"] != mtype:
            raise ValueError(
                f"metric {name!r} already registered as {fam['type']}")
        return fam

    @staticmethod
    def _key(labels: dict) -> Tuple:
        for k in labels:
            if not _LABEL_RE.match(k):
                raise ValueError(f"bad label name {k!r}")
        return tuple(sorted((k, str(v)) for k, v in labels.items()))

    def inc(self, name: str, value: float = 1.0,
            help: Optional[str] = None, **labels) -> None:
        """Add ``value`` (>= 0) to counter ``name``."""
        key = self._key(labels)
        with self._lock:
            fam = self._family(name, "counter", help)
            fam["samples"][key] = fam["samples"].get(key, 0.0) \
                + float(value)

    def set(self, name: str, value: float,
            help: Optional[str] = None, **labels) -> None:
        """Set gauge ``name`` to ``value`` (last write wins)."""
        key = self._key(labels)
        with self._lock:
            fam = self._family(name, "gauge", help)
            fam["samples"][key] = float(value)

    def get(self, name: str, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            fam = self._metrics.get(name)
            if fam is None:
                return 0.0
            return fam["samples"].get(key, 0.0)

    def snapshot(self) -> Dict[str, dict]:
        """Deep-enough copy for the exporter: {name: {type, help,
        samples: {labels_tuple: value}}}."""
        with self._lock:
            return {name: {"type": fam["type"], "help": fam["help"],
                           "samples": dict(fam["samples"])}
                    for name, fam in self._metrics.items()}

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()


#: Process-global registry (the default sink for every recorder).
GLOBAL_COUNTERS = Counters()

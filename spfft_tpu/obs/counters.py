"""Labelled counter/gauge registry for the Prometheus export.

The compile and exchange observability the ISSUE names (plan-build and
jit-compile durations, per-plan wire/busiest-link bytes, HLO collective
counts) are process-wide facts, not per-executor ones — they need a
sink that exists before any server object does and that costs ~a dict
update when tracing is off. This is that sink: metric names follow the
Prometheus data model (``spfft_*``, ``_total`` suffix on counters), the
exporter (:func:`spfft_tpu.obs.exporters.prometheus_text`) renders it
verbatim, and everything else in the process (plan.py, registry,
executor, dist.py) records into the one :data:`GLOBAL_COUNTERS`.

Counters only go up (``inc``); gauges hold the last written value
(``set``). Labels are passed as kwargs and become Prometheus labels.
"""

from __future__ import annotations

import re
import threading
from typing import Dict, Optional, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


class Counters:
    """Thread-safe registry of named counter/gauge families."""

    def __init__(self):
        self._lock = threading.Lock()
        # name -> {"type": "counter"|"gauge", "help": str,
        #          "samples": {(("k","v"), ...): float}}
        self._metrics: Dict[str, dict] = {}

    def _family(self, name: str, mtype: str, help_: Optional[str]):
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r}")
        fam = self._metrics.get(name)
        if fam is None:
            fam = self._metrics[name] = {
                "type": mtype, "help": help_ or name, "samples": {}}
        elif fam["type"] != mtype:
            raise ValueError(
                f"metric {name!r} already registered as {fam['type']}")
        return fam

    @staticmethod
    def _key(labels: dict) -> Tuple:
        for k in labels:
            if not _LABEL_RE.match(k):
                raise ValueError(f"bad label name {k!r}")
        return tuple(sorted((k, str(v)) for k, v in labels.items()))

    def inc(self, name: str, value: float = 1.0,
            help: Optional[str] = None, **labels) -> None:
        """Add ``value`` (>= 0) to counter ``name``."""
        key = self._key(labels)
        with self._lock:
            fam = self._family(name, "counter", help)
            fam["samples"][key] = fam["samples"].get(key, 0.0) \
                + float(value)

    def set(self, name: str, value: float,
            help: Optional[str] = None, **labels) -> None:
        """Set gauge ``name`` to ``value`` (last write wins)."""
        key = self._key(labels)
        with self._lock:
            fam = self._family(name, "gauge", help)
            fam["samples"][key] = float(value)

    def get(self, name: str, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            fam = self._metrics.get(name)
            if fam is None:
                return 0.0
            return fam["samples"].get(key, 0.0)

    def snapshot(self) -> Dict[str, dict]:
        """Deep-enough copy for the exporter: {name: {type, help,
        samples: {labels_tuple: value}}}."""
        with self._lock:
            return {name: {"type": fam["type"], "help": fam["help"],
                           "samples": dict(fam["samples"])}
                    for name, fam in self._metrics.items()}

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()


#: Process-global registry (the default sink for every recorder).
GLOBAL_COUNTERS = Counters()

"""Span tracer: the unified telemetry core (``spfft_tpu.obs``).

The reference ships a dedicated timing subsystem (rt_graph's
``Timer``/``TimingResult`` call tree, compiled in behind SPFFT_TIMING)
because a sparse-FFT pipeline is only tunable when every stage is
attributable. This module carries that idea to the serving era: instead
of three disjoint telemetry islands (``timing.py`` scope timer,
``serve.metrics`` counters, per-round bench JSON), one process-global
:class:`Tracer` records SPANS — named, timestamped intervals carrying a
trace id, a parent link, a track (the lane/device/compile row they draw
on in a trace viewer) and a status — plus instant and counter events.
Exporters (:mod:`~spfft_tpu.obs.exporters`) turn the buffer into Chrome
trace-event JSON (opens in Perfetto / chrome://tracing) and Prometheus
text exposition.

Lifecycle contract (the property the fault tests pin): every span BEGUN
is eventually FINISHED, exactly once, with ``status="error"`` and the
typed error name on failure paths — the serving executor closes a
request's surviving spans whenever it resolves the request's future,
so a crash, an injected fault or a deadline expiry can never leak an
open span. :meth:`Tracer.open_count` is the test's observable.

Cost model: tracing is OFF by default and the disabled path is one
module-global boolean read per checkpoint (budgeted <= 1% on
``serve.bench``, measured in BENCHMARKS.md "Round-10"). Enable with
:func:`enable` or ``SPFFT_TPU_TRACE=1``; bound per-request overhead
further with ``SPFFT_TPU_TRACE_SAMPLE`` (fraction of requests traced,
default 1.0 — the deterministic accumulator samples exactly that
fraction, no RNG). The event buffer is a bounded ring
(``SPFFT_TPU_TRACE_BUFFER`` events, default 65536): a long-lived server
keeps the most recent window and counts drops instead of growing
without bound.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import threading
import time
from collections import deque
from typing import Dict, List, NamedTuple, Optional

#: Environment knobs (read at import; enable()/set_sample_rate() override).
TRACE_ENV = "SPFFT_TPU_TRACE"
SAMPLE_ENV = "SPFFT_TPU_TRACE_SAMPLE"
BUFFER_ENV = "SPFFT_TPU_TRACE_BUFFER"

DEFAULT_BUFFER_EVENTS = 65536

_enabled = os.environ.get(TRACE_ENV) == "1"

#: Flight-recorder overrides (set by obs.recorder, never directly):
#: ``_force_sample`` bypasses the head sampler so tail retention sees
#: every request; ``_trace_complete_hook`` is called with
#: ``(tracer, root_span, status, error)`` as each RequestTrace closes.
_force_sample = False
_trace_complete_hook = None


def active() -> bool:
    """The one-boolean disabled-path check every instrumentation point
    starts with. Module-global so the executor's hot path pays a read,
    not an attribute chain."""
    return _enabled


def force_sampling(on: bool) -> None:
    """Recorder seam: make :meth:`Tracer.sample` return True for every
    request while tail retention is armed (head sampling can stay
    off/low — the recorder needs a tail to retain)."""
    global _force_sample
    _force_sample = bool(on)


def set_trace_complete_hook(hook) -> None:
    """Recorder seam: register (or clear, with None) the callable every
    :meth:`RequestTrace.close` notifies after settling its root span.
    Exceptions from the hook are swallowed — trace completion is on
    request-resolution paths and must never fail them."""
    global _trace_complete_hook
    _trace_complete_hook = hook


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


class Span:
    """One named interval. ``track`` names the row it renders on
    (``lane:high``, ``device:0``, ``compile``, ``exchange``);
    ``trace_id`` groups the spans of one request; ``parent_id`` links
    the stage spans under their request root. ``t1 is None`` while
    open."""

    __slots__ = ("name", "cat", "trace_id", "span_id", "parent_id",
                 "track", "t0", "t1", "status", "error", "args")

    def __init__(self, name, cat, trace_id, span_id, parent_id, track,
                 t0, args=None):
        self.name = name
        self.cat = cat
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.track = track
        self.t0 = t0
        self.t1: Optional[float] = None
        self.status = "ok"
        self.error: Optional[str] = None
        self.args = args

    @property
    def duration(self) -> float:
        return (self.t1 - self.t0) if self.t1 is not None else 0.0


class TraceContext(NamedTuple):
    """The wire-serializable slice of a span a cross-host RPC carries:
    the trace id (stable end-to-end) and the span id of the remote
    parent. Exposes ``span_id`` so it can stand in for a ``parent=``
    argument on the receiving host — :meth:`Tracer.begin` only reads
    ``parent.span_id``, never the rest of the Span. Build one with
    :meth:`Span.context`, restore with ``RequestTrace(..., ctx=...)``."""

    trace_id: int
    span_id: int

    def to_wire(self) -> dict:
        """Plain-dict form for an RPC payload (loopback or real)."""
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @classmethod
    def from_wire(cls, payload: Optional[dict]) -> Optional["TraceContext"]:
        if not payload:
            return None
        return cls(int(payload["trace_id"]), int(payload["span_id"]))


def span_context(span: Optional[Span]) -> Optional[TraceContext]:
    """The propagatable context of ``span`` (None-safe; None when the
    span carries no trace id — an unsampled request propagates
    nothing)."""
    if span is None or span.trace_id is None:
        return None
    return TraceContext(span.trace_id, span.span_id)


class Tracer:
    """Thread-safe bounded span/event recorder.

    Spans: :meth:`begin` / :meth:`finish` (cross-thread: begin on a
    submitter thread, finish on the dispatcher), :meth:`span` (context
    manager, error status captured), :meth:`complete` (an interval
    measured elsewhere, recorded after the fact — plan builds use it).
    Point events: :meth:`instant` (annotations: retries, quarantines),
    :meth:`counter` (numeric series: per-chunk wire bytes).
    """

    def __init__(self, max_events: Optional[int] = None):
        if max_events is None:
            max_events = int(os.environ.get(BUFFER_ENV,
                                            DEFAULT_BUFFER_EVENTS))
        self._lock = threading.Lock()
        self._max_events = max(1, int(max_events))
        self.epoch = time.perf_counter()
        self._events: deque = deque(maxlen=self._max_events)  #: guarded by _lock
        self._open: Dict[int, Span] = {}  #: guarded by _lock
        # GIL-atomic id sources: begin() stamps ids OUTSIDE the lock
        self._span_ids = itertools.count(1)
        self._trace_ids = itertools.count(1)
        self._spans_started = 0   #: guarded by _lock
        self._spans_closed = 0    #: guarded by _lock
        self._dropped = 0         #: guarded by _lock
        self._sample_rate = self._env_sample_rate()  #: guarded by _lock
        self._sample_acc = 0.0    #: guarded by _lock

    @staticmethod
    def _env_sample_rate() -> float:
        try:
            rate = float(os.environ.get(SAMPLE_ENV, "1.0"))
        except ValueError:
            rate = 1.0
        return min(1.0, max(0.0, rate))

    # -- lifecycle ----------------------------------------------------------
    def reset(self) -> None:
        """Drop every buffered event and open span and restart the
        clock (the bench CLI separates warmup from the measured replay
        this way). Quiesce instrumented executors first — a span begun
        before a reset is silently forgotten, not closed."""
        with self._lock:
            self.epoch = time.perf_counter()
            self._events.clear()
            self._open.clear()
            self._spans_started = 0
            self._spans_closed = 0
            self._dropped = 0
            self._sample_acc = 0.0

    def set_sample_rate(self, rate: float) -> None:
        with self._lock:
            self._sample_rate = min(1.0, max(0.0, float(rate)))
            self._sample_acc = 0.0

    def sample(self) -> bool:
        """Deterministic rate sampler: returns True for exactly
        ``sample_rate`` of calls (accumulator, no RNG — a replayed
        trace samples the same requests)."""
        if _force_sample:
            return True
        with self._lock:
            self._sample_acc += self._sample_rate
            if self._sample_acc >= 1.0 - 1e-12:
                self._sample_acc -= 1.0
                return True
            return False

    def new_trace_id(self) -> int:
        return next(self._trace_ids)

    # -- spans --------------------------------------------------------------
    def begin(self, name: str, cat: str = "serve",
              trace_id: Optional[int] = None,
              parent: Optional[Span] = None,
              track: Optional[str] = None,
              args: Optional[dict] = None) -> Span:
        span = Span(name, cat, trace_id, next(self._span_ids),
                    parent.span_id if parent is not None else None,
                    track, time.perf_counter(), args)
        with self._lock:
            self._spans_started += 1
            self._open[span.span_id] = span
        return span

    def finish(self, span: Optional[Span], status: str = "ok",
               error: Optional[str] = None,
               args: Optional[dict] = None) -> None:
        """Close ``span`` (idempotent — a second finish is a no-op, so
        failure paths can close defensively)."""
        if span is None:
            return
        with self._lock:
            if self._open.pop(span.span_id, None) is None:
                return  # already closed
            span.t1 = time.perf_counter()
            span.status = status
            if error is not None:
                span.error = error
            if args:
                span.args = dict(span.args or {}, **args)
            self._spans_closed += 1
            self._append_locked(span)

    def complete(self, name: str, t0: float, t1: float,
                 cat: str = "serve", trace_id: Optional[int] = None,
                 parent: Optional[Span] = None,
                 track: Optional[str] = None, status: str = "ok",
                 error: Optional[str] = None,
                 args: Optional[dict] = None) -> Span:
        """Record an interval measured by the caller (never counted
        open)."""
        span = Span(name, cat, trace_id, next(self._span_ids),
                    parent.span_id if parent is not None else None,
                    track, t0, args)
        span.t1 = t1
        span.status = status
        span.error = error
        with self._lock:
            self._spans_started += 1
            self._spans_closed += 1
            self._append_locked(span)
        return span

    @contextlib.contextmanager
    def span(self, name: str, **kwargs):
        sp = self.begin(name, **kwargs)
        try:
            yield sp
        except BaseException as exc:
            self.finish(sp, status="error", error=type(exc).__name__)
            raise
        else:
            self.finish(sp)

    # -- point events -------------------------------------------------------
    def instant(self, name: str, cat: str = "serve",
                track: Optional[str] = None,
                trace_id: Optional[int] = None,
                args: Optional[dict] = None) -> None:
        with self._lock:
            self._append_locked({"type": "instant", "name": name,
                                 "cat": cat, "track": track,
                                 "trace_id": trace_id,
                                 "ts": time.perf_counter(),
                                 "args": args})

    def counter(self, name: str, values: Dict[str, float],
                cat: str = "serve", track: Optional[str] = None) -> None:
        """One sample of a numeric series (renders as a stacked counter
        track in Perfetto)."""
        with self._lock:
            self._append_locked({"type": "counter", "name": name,
                                 "cat": cat, "track": track,
                                 "ts": time.perf_counter(),
                                 "args": dict(values)})

    # lock: holds(_lock)
    def _append_locked(self, event) -> None:
        if len(self._events) >= self._max_events:
            self._dropped += 1
        self._events.append(event)

    # -- reading ------------------------------------------------------------
    def events(self) -> List:
        """Snapshot of the buffered CLOSED events (spans + instants +
        counters), oldest first."""
        with self._lock:
            return list(self._events)

    def open_count(self) -> int:
        with self._lock:
            return len(self._open)

    def open_names(self) -> List[str]:
        """Names of still-open spans — the zero-leak test's diagnostic."""
        with self._lock:
            return sorted(s.name for s in self._open.values())

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"started": self._spans_started,
                    "closed": self._spans_closed,
                    "open": len(self._open),
                    "buffered": len(self._events),
                    "dropped": self._dropped,
                    "sample_rate": self._sample_rate}


class RequestTrace:
    """Per-request trace handle the serving executor threads through
    its pipeline. Owns the ``serve.request`` root span plus whichever
    per-request stage spans are currently open; :meth:`close` settles
    EVERYTHING still open — the single call every resolution path
    (success, typed failure, crash sweep) funnels through, which is how
    the zero-unclosed-spans guarantee holds."""

    __slots__ = ("tracer", "trace_id", "lane", "root", "open")

    def __init__(self, tracer: Tracer, lane: str,
                 args: Optional[dict] = None,
                 ctx: Optional[TraceContext] = None):
        self.tracer = tracer
        # A propagated context (cross-host RPC) pins the trace id and
        # parents this request's root under the remote frontend span —
        # one trace id end-to-end, frontend parent / host-lane child.
        self.trace_id = ctx.trace_id if ctx is not None \
            else tracer.new_trace_id()
        self.lane = f"lane:{lane}"
        # span: closed-by(RequestTrace.close)
        self.root = tracer.begin("serve.request", trace_id=self.trace_id,
                                 parent=ctx, track=self.lane, args=args)
        self.open: Dict[str, Span] = {}

    def context(self) -> Optional[TraceContext]:
        """Propagatable context of this request's root span (None once
        closed)."""
        return span_context(self.root)

    def begin(self, name: str, track: Optional[str] = None,
              args: Optional[dict] = None) -> Span:
        # span: closed-by(RequestTrace.finish)
        sp = self.tracer.begin(name, trace_id=self.trace_id,
                               parent=self.root,
                               track=track or self.lane, args=args)
        self.open[name] = sp
        return sp

    def finish(self, name: str, status: str = "ok",
               error: Optional[str] = None) -> None:
        sp = self.open.pop(name, None)
        if sp is not None:
            self.tracer.finish(sp, status=status, error=error)

    def annotate(self, name: str, **args) -> None:
        """Attach a point annotation (retry, bucket fallback, ...) to
        this request's trace."""
        self.tracer.instant(name, track=self.lane,
                            trace_id=self.trace_id, args=args or None)

    def close(self, status: str = "ok",
              error: Optional[str] = None) -> None:
        for name in list(self.open):
            self.finish(name, status=status, error=error)
        root = self.root
        if root is not None:
            self.tracer.finish(root, status=status, error=error)
            self.root = None
            hook = _trace_complete_hook
            if hook is not None:
                try:
                    hook(self.tracer, root, status, error)
                except Exception:  # never fail a resolution path
                    pass


#: Process-global tracer (the exporters' and executor's default).
GLOBAL_TRACER = Tracer()

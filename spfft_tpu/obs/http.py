"""HTTP scrape endpoint: ``/metrics``, ``/healthz``, ``/configz``.

The fleet-facing sliver of the pod-scale ROADMAP item, pulled forward:
a stdlib ``ThreadingHTTPServer`` (no new dependencies) that serves

* ``GET /metrics``  — ``obs.prometheus_text()`` over the bound
  ``ServeMetrics``/``PlanRegistry`` plus the process-global counter
  registry, in the text exposition format a Prometheus scraper
  consumes directly;
* ``GET /healthz``  — the executor's ``health()`` snapshot (or the
  bare ``ServeMetrics.health()`` when no executor is bound) as JSON;
  HTTP 200 while the state is servable (healthy / degraded /
  draining), 503 once it is ``failed`` — a load balancer's readiness
  check works out of the box;
* ``GET /configz``  — the live control-plane knob values (executor
  required), so an operator can see what the controller has retuned
  without log archaeology;
* ``GET /incidentz`` — trigger a flight-recorder incident capture NOW
  (``incident_fn`` hook — a pod frontend binds its pod-wide
  :meth:`~spfft_tpu.serve.cluster.PodFrontend.capture_incident`;
  otherwise the recorder's local capture) and return the written
  bundle path as JSON; 503 when the recorder is disarmed or the
  capture failed.

Opt-in: nothing listens unless a server is started —
``serve.bench --metrics-port N`` or the ``SPFFT_TPU_METRICS_PORT``
env var (:func:`port_from_env`); port 0 binds an ephemeral port
(returned by :meth:`MetricsServer.start`). The server binds
``127.0.0.1`` by default — exposing it wider is an explicit operator
choice (``host=``).

Every handler renders from the same one-lock snapshots the exporters
use, so a scrape under live traffic sees a mutually consistent view.
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .exporters import prometheus_text

#: Env opt-in read by serve.bench (and embedders via port_from_env).
METRICS_PORT_ENV = "SPFFT_TPU_METRICS_PORT"

#: Content type of the Prometheus text exposition format.
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: Health states a readiness check should treat as servable.
SERVABLE_STATES = ("healthy", "degraded", "draining")


def port_from_env() -> Optional[int]:
    """The ``SPFFT_TPU_METRICS_PORT`` opt-in, or None (unset/invalid
    values disable rather than crash a server boot)."""
    raw = os.environ.get(METRICS_PORT_ENV)
    if not raw:
        return None
    try:
        port = int(raw)
    except ValueError:
        return None
    return port if 0 <= port <= 65535 else None


class MetricsServer:
    """Background scrape endpoint over one executor's telemetry.

    ``executor`` binds ``/healthz`` (pool detail + knob values) and
    ``/configz``; ``metrics``/``registry`` feed ``/metrics`` (both
    default to the executor's when an executor is given). Use as a
    context manager, or :meth:`start` / :meth:`stop`.
    """

    def __init__(self, metrics=None, registry=None, executor=None,
                 port: int = 0, host: str = "127.0.0.1",
                 text_fn=None, health_fn=None, incident_fn=None):
        if executor is not None:
            metrics = metrics if metrics is not None else executor.metrics
            registry = registry if registry is not None \
                else executor.registry
        self.metrics = metrics
        self.registry = registry
        self.executor = executor
        # Aggregation hooks: a pod frontend overrides what /metrics
        # renders (its merged multi-host exposition), what /healthz
        # reports (worst-lane-health-wins) and what /incidentz
        # captures (the pod-wide bundle) without subclassing the
        # handler; None keeps the single-process defaults.
        self.text_fn = text_fn
        self.health_fn = health_fn
        self.incident_fn = incident_fn
        self.host = host
        self.port = int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- handler -----------------------------------------------------------
    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet by design
                pass

            def _send(self, code: int, body: str, ctype: str) -> None:
                data = body.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        if server.text_fn is not None:
                            body = server.text_fn()
                        else:
                            body = prometheus_text(
                                metrics=server.metrics,
                                registry=server.registry)
                        self._send(200, body, PROM_CONTENT_TYPE)
                    elif path == "/healthz":
                        if server.health_fn is not None:
                            snap = server.health_fn()
                        elif server.executor is not None:
                            snap = server.executor.health()
                        elif server.metrics is not None:
                            snap = server.metrics.health()
                        else:
                            snap = {"state": "unknown"}
                        code = 200 if snap.get("state",
                                               "unknown") \
                            in SERVABLE_STATES else 503
                        self._send(code, json.dumps(snap, default=str),
                                   "application/json")
                    elif path == "/configz":
                        if server.executor is None:
                            self._send(404, "no executor bound\n",
                                       "text/plain")
                        else:
                            self._send(200, json.dumps(
                                server.executor.config.snapshot()),
                                "application/json")
                    elif path == "/incidentz":
                        from . import recorder as _recorder
                        if server.incident_fn is not None:
                            path_ = server.incident_fn("http")
                        elif _recorder.recorder_active():
                            path_ = _recorder.capture_incident("http")
                        else:
                            self._send(503, json.dumps(
                                {"error": "recorder disarmed"}),
                                "application/json")
                            return
                        if path_ is None:
                            self._send(503, json.dumps(
                                {"error": "capture failed"}),
                                "application/json")
                        else:
                            self._send(200, json.dumps(
                                {"path": path_}), "application/json")
                    else:
                        self._send(404, "try /metrics, /healthz, "
                                        "/configz, /incidentz\n",
                                   "text/plain")
                except Exception as exc:  # a broken scrape must not
                    try:                  # kill the handler thread
                        self._send(500, f"{type(exc).__name__}: "
                                        f"{exc}\n", "text/plain")
                    except Exception:
                        pass

        return Handler

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> int:
        """Bind and serve on a daemon thread; returns the bound port
        (meaningful with ``port=0``). Idempotent."""
        if self._httpd is None:
            self._httpd = ThreadingHTTPServer(
                (self.host, self.port), self._make_handler())
            self._httpd.daemon_threads = True
            self.port = self._httpd.server_address[1]
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="spfft-metrics-http", daemon=True)
            self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def __enter__(self) -> "MetricsServer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

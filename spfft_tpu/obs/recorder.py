"""Black-box flight recorder: event journal, tail-retained traces,
incident bundles.

The pod survives failures the reference library never faced — lane
death, epoch fencing, wire-rung declines, fused demotions — but
explaining an incident after the fact used to require having had
tracing enabled, sampled IN, and scraped at the right moment. This
module is the always-on black box that closes that gap, in three
bounded pieces:

* **Structured event journal** — one process-global, lock-disciplined
  ring of typed events. Every decision seam the package already
  instruments with counters ALSO emits one :func:`record_event` call:
  controller knob moves, SLO page rising edges, wire-rung
  resolutions/declines, fused demotions/re-probes, device
  quarantine/probation, store degradation/re-probe, registry build
  failures, lane death/probe/readmit, membership transitions and
  elections, fault-site firings, executor health transitions. Event
  kinds and their attribute keys are DECLARED in :data:`EVENT_SPECS`
  (mirroring ``METRIC_SPECS``) and statically enforced by the
  ``event-registry`` analyzer checker — a typo'd kind cannot become a
  silently-new event stream.
* **Tail-based trace retention** — completed request traces land in a
  short holding ring and are *promoted* to a retained ring when they
  errored, ran over a latency threshold (p99-relative against the live
  ``ServeMetrics`` reservoirs), or were explicitly flagged
  (:func:`flag_trace`). Head sampling can stay off/low; the
  interesting traces survive anyway. Enabling the recorder forces span
  recording on (and bypasses the head sampler) so there is a tail to
  retain.
* **Incident bundles** — :func:`capture_incident` atomically writes a
  versioned, self-contained JSON bundle (journal slice, retained
  traces in Chrome-trace event format, Prometheus snapshot, knob
  values + bounded config history, health, platform summary) under a
  bounded, GC'd incident directory. Auto-triggered (debounced) on SLO
  page rising edges, executor health degrade/fail transitions and
  lane death; ``PodFrontend.capture_incident`` gathers every alive
  host's bundle over the ops wire into one pod bundle.

Cost model: the journal is always on (decision-seam events are rare —
a lock + deque append each). Trace retention costs one module-global
read per request when the recorder is OFF; when ON, the per-request
cost is the span recording itself plus an O(1) holding-ring append —
promotion (the O(ring) event scan) only runs for retained traces.
``overhead_probe`` measures the A/B deterministically for the
``recorder_overhead`` bench gate. A failing bundle write is typed and
non-fatal (``obs.capture`` fault site): recording never takes down
serving.
"""

from __future__ import annotations

import json
import os
import platform as _platform
import sys
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from .counters import GLOBAL_COUNTERS
from . import trace as _trace
from .trace import GLOBAL_TRACER, Span, Tracer

#: Environment knobs (read at enable time; arguments override).
RECORDER_ENV = "SPFFT_TPU_RECORDER"
EVENT_BUFFER_ENV = "SPFFT_TPU_EVENT_BUFFER"
INCIDENT_DIR_ENV = "SPFFT_TPU_INCIDENT_DIR"
INCIDENT_KEEP_ENV = "SPFFT_TPU_INCIDENT_KEEP"
INCIDENT_MIN_INTERVAL_ENV = "SPFFT_TPU_INCIDENT_MIN_INTERVAL_S"
HOLD_RING_ENV = "SPFFT_TPU_RECORDER_HOLD"
RETAIN_RING_ENV = "SPFFT_TPU_RECORDER_RETAIN"
SLOW_FACTOR_ENV = "SPFFT_TPU_RECORDER_SLOW_FACTOR"
SLOW_ABS_ENV = "SPFFT_TPU_RECORDER_SLOW_S"

DEFAULT_EVENT_BUFFER = 4096
DEFAULT_HOLD = 256
DEFAULT_RETAIN = 32
DEFAULT_INCIDENT_KEEP = 16
DEFAULT_MIN_INTERVAL_S = 30.0
#: Default p99-relative promotion threshold: a trace slower than
#: ``factor * latency_p99`` of the live reservoir is retained.
DEFAULT_SLOW_FACTOR = 3.0

#: Bundle format version (validators refuse unknown majors).
BUNDLE_VERSION = 1

#: THE event-kind registry: every journal event any part of the
#: process emits — through :func:`record_event` — declared exactly
#: once, as ``kind: (category, help, declared attr keys)``. The static
#: event-registry checker (``python -m spfft_tpu.analysis``) fails the
#: build on an emitted kind missing here, on a declared kind nothing
#: emits, and on attrs outside the declared key set; at runtime
#: :func:`record_event` drops undeclared kinds/attrs (counted, never
#: raising) — the journal can never take down serving.
EVENT_SPECS: Dict[str, Tuple[str, str, Tuple[str, ...]]] = {
    # control plane
    "control.knob":
        ("control", "Accepted control-plane knob move (controller or "
                    "operator; config.set is the single funnel).",
         ("knob", "old", "new", "reason", "source")),
    "slo.alert":
        ("control", "SLO multi-window page condition entered (rising "
                    "edge of spfft_slo_window_alerts_total).",
         ("slo",)),
    # distributed wire precision ladder
    "wire.resolve":
        ("exchange", "Wire-compression rung resolved at plan build.",
         ("requested", "resolved", "probe_error")),
    "wire.decline":
        ("exchange", "One wire rung declined during resolution, with "
                     "the typed reason.",
         ("rung", "reason")),
    # fused-kernel runtime demotion ladder
    "fused.demote":
        ("plan", "Fused kernel direction demoted to the unfused "
                 "composition after a device-attributed failure.",
         ("which", "reason", "permanent")),
    "fused.readmit":
        ("plan", "Fused kernel direction readmitted after a "
                 "successful re-probe.",
         ("which", "probes")),
    # serving executor device pool + lifecycle
    "device.quarantine":
        ("serve", "Pool device quarantined after consecutive "
                  "device-attributed failures.",
         ("device", "backoff_s")),
    "device.probation":
        ("serve", "Quarantined device entered probation (one canary "
                  "request).",
         ("device", "backoff_s")),
    "device.readmit":
        ("serve", "Probation canary succeeded; device readmitted.",
         ("device",)),
    "health.transition":
        ("serve", "Executor lifecycle state change (healthy/degraded/"
                  "draining/failed).",
         ("state", "prev")),
    # plan-artifact store degradation ladder
    "store.degrade":
        ("store", "Plan-artifact store degraded to the memory-only "
                  "tier after a persistent disk fault.",
         ("reason", "interval_s")),
    "store.reprobe":
        ("store", "Degraded-store disk re-probe outcome.",
         ("outcome",)),
    # plan registry
    "registry.build_failure":
        ("compile", "A registry plan build raised (the failure is "
                    "broadcast to every coalesced waiter).",
         ("error",)),
    # pod cluster lane lifecycle
    "lane.death":
        ("cluster", "Host lane marked dead by the pod frontend.",
         ("host",)),
    "lane.probe":
        ("cluster", "Resurrection-ladder health probe of a dead lane.",
         ("host", "outcome")),
    "lane.readmit":
        ("cluster", "Dead lane readmitted after a successful probe "
                    "and strict prewarm.",
         ("host",)),
    # lease-based membership
    "membership.transition":
        ("membership", "Lease-ladder state transition at the view "
                       "coordinator (epoch bump).",
         ("host", "to", "epoch")),
    "membership.elect":
        ("membership", "A node promoted itself coordinator (election "
                       "over the adopted view).",
         ("host", "epoch")),
    # package-wide fault seam
    "fault.fired":
        ("faults", "A FaultPlan checkpoint fired an injected fault.",
         ("site", "kind")),
    # the recorder itself
    "incident.capture":
        ("obs", "An incident bundle capture was attempted.",
         ("reason", "outcome")),
}


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _scalar(v):
    """JSON-safe attribute value (numpy scalars and exceptions become
    strings; containers are repr-trimmed)."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    try:
        import numpy as np
        if isinstance(v, np.generic):
            return v.item()
    except Exception:  # pragma: no cover - numpy always present here
        pass
    return str(v)[:200]


class EventJournal:
    """Bounded, thread-safe ring of typed events (the black box's
    decision log). Always on: appends are a lock + deque push."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            capacity = _env_int(EVENT_BUFFER_ENV, DEFAULT_EVENT_BUFFER)
        self._lock = threading.Lock()
        self._capacity = max(16, int(capacity))
        self._ring: deque = deque(maxlen=self._capacity)  #: guarded by _lock
        self._seq = 0        #: guarded by _lock
        self._dropped = 0    #: guarded by _lock

    def record(self, kind: str, attrs: Dict) -> None:
        spec = EVENT_SPECS.get(kind)
        if spec is None:
            GLOBAL_COUNTERS.inc("spfft_recorder_events_dropped_total",
                                reason="undeclared_kind")
            return
        declared = spec[2]
        clean = {k: _scalar(v) for k, v in attrs.items()
                 if k in declared}
        entry = {"kind": kind, "cat": spec[0], "ts": time.time(),
                 "attrs": clean}
        with self._lock:
            self._seq += 1
            entry["seq"] = self._seq
            if len(self._ring) >= self._capacity:
                self._dropped += 1
            self._ring.append(entry)
        GLOBAL_COUNTERS.inc("spfft_recorder_events_total", kind=kind)

    def snapshot(self, limit: Optional[int] = None) -> List[Dict]:
        """Oldest-first copy of the buffered events (the bundle's
        journal slice); ``limit`` keeps the most recent N."""
        with self._lock:
            events = list(self._ring)
        if limit is not None and len(events) > limit:
            events = events[-int(limit):]
        return events

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"buffered": len(self._ring), "seq": self._seq,
                    "dropped": self._dropped,
                    "capacity": self._capacity}

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._seq = 0
            self._dropped = 0


#: Process-global journal (the single sink record_event feeds).
GLOBAL_JOURNAL = EventJournal()


def record_event(kind: str, /, **attrs) -> None:
    """Append one typed event to the process journal. ``kind`` must be
    declared in :data:`EVENT_SPECS` (undeclared kinds are counted and
    dropped, never raised — the decision seams this is called from
    must not gain a new failure mode). This is the ONE line a
    subsystem adds per decision seam, next to its existing counter."""
    GLOBAL_JOURNAL.record(kind, attrs)


# ---------------------------------------------------------------------------
# tail-based trace retention
# ---------------------------------------------------------------------------

class _Retention:
    """Holding + retained rings for completed request traces."""

    def __init__(self):
        self._lock = threading.Lock()
        self._hold_cap = _env_int(HOLD_RING_ENV, DEFAULT_HOLD)
        self._retain_cap = _env_int(RETAIN_RING_ENV, DEFAULT_RETAIN)
        #: holding ring: trace_id -> completion meta  (guarded by _lock)
        self._holding: "deque[dict]" = deque(maxlen=self._hold_cap)
        #: retained ring: promoted trace dicts  (guarded by _lock)
        self._retained: "deque[dict]" = deque(maxlen=self._retain_cap)
        self._slow_factor = _env_float(SLOW_FACTOR_ENV,
                                       DEFAULT_SLOW_FACTOR)
        self._slow_abs = _env_float(SLOW_ABS_ENV, 0.0)
        #: cached p99 threshold + closes since refresh (guarded by _lock)
        self._p99_cache = 0.0
        self._p99_age = 0
        self._latency_fn: Optional[Callable[[], float]] = None

    def set_latency_source(self, fn: Optional[Callable[[], float]]):
        """Register a zero-arg callable returning the live latency p99
        in seconds (``ServeMetrics`` wires its reservoir here); the
        slow-promotion threshold is ``slow_factor * p99``, refreshed
        every 64 completions so the hot path never recomputes
        percentiles per request."""
        with self._lock:
            self._latency_fn = fn
            self._p99_age = 64  # force refresh on next completion

    def _slow_threshold_locked(self) -> float:
        self._p99_age += 1
        if self._p99_age >= 64 and self._latency_fn is not None:
            self._p99_age = 0
            try:
                self._p99_cache = float(self._latency_fn() or 0.0)
            except Exception:
                self._p99_cache = 0.0
        if self._p99_cache > 0.0:
            return self._slow_factor * self._p99_cache
        return self._slow_abs  # 0.0 disables slow promotion

    def note_complete(self, tracer: Tracer, root: Span, status: str,
                      error: Optional[str]) -> None:
        meta = {"trace_id": root.trace_id, "name": root.name,
                "status": status, "error": error,
                "duration_s": root.duration, "ts": time.time()}
        reason = None
        with self._lock:
            self._holding.append(meta)
            if status != "ok" or error:
                reason = "error"
            else:
                thresh = self._slow_threshold_locked()
                if thresh > 0.0 and root.duration > thresh:
                    reason = "slow"
        if reason is not None:
            self._promote(tracer, meta, reason)

    def flag(self, trace_id: int, tracer: Optional[Tracer] = None,
             reason: str = "flagged") -> bool:
        """Explicitly promote a held (or still-buffered) trace."""
        tracer = tracer or GLOBAL_TRACER
        with self._lock:
            meta = next((m for m in self._holding
                         if m["trace_id"] == trace_id), None)
        if meta is None:
            meta = {"trace_id": trace_id, "name": "serve.request",
                    "status": "ok", "error": None, "duration_s": 0.0,
                    "ts": time.time()}
        return self._promote(tracer, meta, reason)

    def _promote(self, tracer: Tracer, meta: dict, reason: str) -> bool:
        from .exporters import trace_events
        tid = meta["trace_id"]
        raw = [ev for ev in tracer.events()
               if (ev.trace_id if isinstance(ev, Span)
                   else ev.get("trace_id")) == tid]
        entry = dict(meta)
        entry["reason"] = reason
        entry["events"] = trace_events(tracer, events=raw, bare=True)
        with self._lock:
            # idempotent per trace id: a flag after an error-promotion
            # replaces rather than duplicates
            for i, old in enumerate(self._retained):
                if old["trace_id"] == tid:
                    self._retained[i] = entry
                    break
            else:
                self._retained.append(entry)
        GLOBAL_COUNTERS.inc("spfft_recorder_traces_retained_total",
                            reason=reason)
        return bool(raw)

    def retained(self) -> List[dict]:
        with self._lock:
            return [dict(e) for e in self._retained]

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"holding": len(self._holding),
                    "retained": len(self._retained)}

    def reset(self) -> None:
        with self._lock:
            self._holding.clear()
            self._retained.clear()
            self._p99_cache = 0.0
            self._p99_age = 0


_RETENTION = _Retention()

# -- recorder lifecycle -----------------------------------------------------

_lifecycle_lock = threading.Lock()
_active = False
_incident_dir: Optional[str] = None
_incident_keep = DEFAULT_INCIDENT_KEEP
_min_interval_s = DEFAULT_MIN_INTERVAL_S
_auto = True
_last_auto = 0.0
_incident_seq = 0
_capture_lock = threading.Lock()
#: optional pod-wide capturer (PodFrontend.capture_incident) the auto
#: triggers route through instead of a local-only bundle
_capturer: Optional[Callable[[str], Optional[str]]] = None
#: optional health-snapshot provider for the bundle (executor/pod)
_health_fn: Optional[Callable[[], dict]] = None


def recorder_active() -> bool:
    """One module-global boolean: is tail retention armed?"""
    return _active


def enable_recorder(incident_dir: Optional[str] = None,
                    keep: Optional[int] = None,
                    min_interval_s: Optional[float] = None,
                    auto: bool = True) -> None:
    """Arm the flight recorder: force span recording on (bypassing the
    head sampler — there must be a tail to retain), configure the
    incident directory (argument > ``SPFFT_TPU_INCIDENT_DIR`` env >
    disabled) and the auto-capture debounce. Idempotent."""
    global _active, _incident_dir, _incident_keep, _min_interval_s, \
        _auto, _last_auto
    with _lifecycle_lock:
        _active = True
        _incident_dir = (incident_dir
                         or os.environ.get(INCIDENT_DIR_ENV) or None)
        _incident_keep = max(1, keep if keep is not None
                             else _env_int(INCIDENT_KEEP_ENV,
                                           DEFAULT_INCIDENT_KEEP))
        _min_interval_s = (min_interval_s if min_interval_s is not None
                           else _env_float(INCIDENT_MIN_INTERVAL_ENV,
                                           DEFAULT_MIN_INTERVAL_S))
        _auto = bool(auto)
        _last_auto = 0.0
    _trace.enable()
    _trace.force_sampling(True)
    _trace.set_trace_complete_hook(_RETENTION.note_complete)


def disable_recorder() -> None:
    """Disarm tail retention and the auto triggers (the journal stays
    on — it is the always-on black box). Does NOT disable tracing:
    callers that enabled it separately keep their spans."""
    global _active, _capturer, _health_fn
    with _lifecycle_lock:
        _active = False
        _capturer = None
        _health_fn = None
    _trace.force_sampling(False)
    _trace.set_trace_complete_hook(None)
    _RETENTION.reset()


def recorder_from_env() -> bool:
    """Arm the recorder when ``SPFFT_TPU_RECORDER=1`` (embedders call
    this once at boot; returns whether it armed)."""
    if os.environ.get(RECORDER_ENV) == "1":
        enable_recorder()
        return True
    return False


def set_incident_capturer(fn: Optional[Callable[[str], Optional[str]]]
                          ) -> None:
    """Route auto captures through ``fn(reason) -> path`` (the pod
    frontend registers its pod-wide capture here); None restores the
    local-bundle default."""
    global _capturer
    with _lifecycle_lock:
        _capturer = fn


def set_health_provider(fn: Optional[Callable[[], dict]]) -> None:
    """Register the health snapshot the bundle embeds (an executor's
    or pod frontend's ``health()``)."""
    global _health_fn
    with _lifecycle_lock:
        _health_fn = fn


def set_latency_source(fn: Optional[Callable[[], float]]) -> None:
    """See :meth:`_Retention.set_latency_source`."""
    _RETENTION.set_latency_source(fn)


def flag_trace(trace_id: int, reason: str = "flagged") -> bool:
    """Explicitly retain a completed trace by id."""
    return _RETENTION.flag(trace_id, reason=reason)


def retained_traces() -> List[dict]:
    """Snapshot of the retained (promoted) traces."""
    return _RETENTION.retained()


def recorder_stats() -> Dict:
    """Journal + retention counters (tests and ops)."""
    out = dict(GLOBAL_JOURNAL.stats())
    out.update(_RETENTION.stats())
    out["active"] = _active
    out["incident_dir"] = _incident_dir
    return out


def reset_recorder() -> None:
    """Drop journal + rings (bench/test isolation; keeps the armed
    state and configuration)."""
    GLOBAL_JOURNAL.reset()
    _RETENTION.reset()


# ---------------------------------------------------------------------------
# incident bundles
# ---------------------------------------------------------------------------

def build_incident_bundle(reason: str, host: Optional[str] = None
                          ) -> dict:
    """One self-contained, JSON-clean snapshot of everything the black
    box knows right now. Never raises — a section that fails to render
    degrades to an ``{"error": ...}`` stub (recording must never take
    down serving)."""
    bundle = {
        "version": BUNDLE_VERSION,
        "kind": "host",
        "reason": str(reason),
        "host": host or f"pid-{os.getpid()}",
        "captured_at": time.time(),
        "events": GLOBAL_JOURNAL.snapshot(),
        "traces": _RETENTION.retained(),
        "recorder": recorder_stats(),
    }
    try:
        from .exporters import prometheus_text
        bundle["prometheus"] = prometheus_text()
    except Exception as exc:
        bundle["prometheus"] = ""
        bundle["prometheus_error"] = repr(exc)[:200]
    try:
        from ..control.config import global_config
        cfg = global_config()
        bundle["config"] = {"knobs": cfg.snapshot(),
                            "history": cfg.decisions()}
    except Exception as exc:
        bundle["config"] = {"error": repr(exc)[:200]}
    fn = _health_fn
    if fn is not None:
        try:
            bundle["health"] = fn()
        except Exception as exc:
            bundle["health"] = {"error": repr(exc)[:200]}
    else:
        bundle["health"] = {}
    bundle["platform"] = {
        "python": sys.version.split()[0],
        "platform": _platform.platform(),
        "pid": os.getpid(),
    }
    return bundle


def _gc_incident_dir(directory: str, keep: int) -> None:
    try:
        names = [n for n in os.listdir(directory)
                 if n.startswith("incident-") and n.endswith(".json")]
        if len(names) <= keep:
            return
        paths = sorted((os.path.join(directory, n) for n in names),
                       key=lambda p: (os.path.getmtime(p), p))
        for path in paths[:len(paths) - keep]:
            os.unlink(path)
    except OSError:  # pragma: no cover - GC is best-effort
        pass


def write_bundle(bundle: dict, directory: Optional[str] = None,
                 keep: Optional[int] = None) -> str:
    """Atomically persist ``bundle`` under the incident dir (tmp-file +
    rename — a crashed writer leaves a ``.tmp``, never a torn
    ``.json``), then GC the directory down to ``keep`` bundles.
    Raises on failure; :func:`capture_incident` is the non-fatal
    wrapper."""
    global _incident_seq
    directory = (directory or _incident_dir
                 or os.environ.get(INCIDENT_DIR_ENV))
    if not directory:
        raise ValueError("no incident directory configured "
                         f"(enable_recorder(incident_dir=...) or "
                         f"{INCIDENT_DIR_ENV})")
    keep = keep if keep is not None else _incident_keep
    os.makedirs(directory, exist_ok=True)
    with _lifecycle_lock:
        _incident_seq += 1
        seq = _incident_seq
    stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
    name = f"incident-{stamp}-{os.getpid()}-{seq:04d}.json"
    path = os.path.join(directory, name)
    tmp = path + ".tmp"
    from .. import faults as _faults
    try:
        _faults.check_site("obs.capture")
        with open(tmp, "w") as f:
            json.dump(bundle, f, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _gc_incident_dir(directory, keep)
    return path


def capture_incident(reason: str, directory: Optional[str] = None,
                     host: Optional[str] = None) -> Optional[str]:
    """Build + atomically write a local incident bundle; returns the
    path, or None when the write failed (typed, counted, non-fatal —
    the ``obs.capture`` fault site fires here in chaos storms).
    Serialized: concurrent captures queue on one lock."""
    with _capture_lock:
        bundle = build_incident_bundle(reason, host=host)
        try:
            path = write_bundle(bundle, directory=directory)
        except Exception as exc:
            GLOBAL_COUNTERS.inc("spfft_recorder_incident_failures_total")
            record_event("incident.capture", reason=reason,
                         outcome=f"failed: {type(exc).__name__}")
            return None
    GLOBAL_COUNTERS.inc("spfft_recorder_incidents_total",
                        trigger=reason.split(":", 1)[0])
    record_event("incident.capture", reason=reason, outcome="written")
    return path


def maybe_auto_capture(trigger: str, reason: Optional[str] = None
                       ) -> Optional[str]:
    """Debounced auto-capture hook the decision seams call on their
    rising edges (SLO page, health degrade/fail, lane death). No-op
    unless the recorder is armed, auto capture is on, an incident dir
    (or pod capturer) is configured, and the debounce interval has
    passed. Never raises."""
    global _last_auto
    if not _active or not _auto:
        return None
    capturer = _capturer
    if capturer is None and not (_incident_dir
                                 or os.environ.get(INCIDENT_DIR_ENV)):
        return None
    now = time.monotonic()
    with _lifecycle_lock:
        if _last_auto and now - _last_auto < _min_interval_s:
            return None
        _last_auto = now
    full = f"{trigger}:{reason}" if reason else trigger
    try:
        if capturer is not None:
            return capturer(full)
        return capture_incident(full)
    except Exception:  # pragma: no cover - capturers are non-fatal
        GLOBAL_COUNTERS.inc("spfft_recorder_incident_failures_total")
        return None


# ---------------------------------------------------------------------------
# pod bundles + validation
# ---------------------------------------------------------------------------

def merge_pod_bundle(reason: str, host_bundles: Dict[str, dict]) -> dict:
    """Merge per-host bundles into one pod bundle with a single
    host-labelled timeline (events sorted by wall timestamp, then
    per-host sequence — one ordered story across the pod)."""
    timeline: List[dict] = []
    for host, sub in host_bundles.items():
        for ev in (sub or {}).get("events", ()):
            ev = dict(ev)
            ev["host"] = host
            timeline.append(ev)
    timeline.sort(key=lambda e: (e.get("ts", 0.0), e.get("host", ""),
                                 e.get("seq", 0)))
    return {
        "version": BUNDLE_VERSION,
        "kind": "pod",
        "reason": str(reason),
        "captured_at": time.time(),
        "hosts": dict(host_bundles),
        "timeline": timeline,
    }


def validate_bundle(bundle: dict) -> List[str]:
    """Structural schema validation of a host or pod bundle; returns a
    list of failure messages (empty = valid). The round-trip check the
    chaos harness and tier-1 incident test run over every captured
    file."""
    failures: List[str] = []
    if not isinstance(bundle, dict):
        return ["bundle is not a JSON object"]
    if bundle.get("version") != BUNDLE_VERSION:
        failures.append(f"unknown bundle version "
                        f"{bundle.get('version')!r}")
    kind = bundle.get("kind")
    if kind not in ("host", "pod"):
        failures.append(f"unknown bundle kind {kind!r}")
    if not isinstance(bundle.get("reason"), str):
        failures.append("reason missing or not a string")
    if not isinstance(bundle.get("captured_at"), (int, float)):
        failures.append("captured_at missing or not a number")
    if kind == "pod":
        hosts = bundle.get("hosts")
        if not isinstance(hosts, dict) or not hosts:
            failures.append("pod bundle has no hosts")
            hosts = {}
        for host, sub in hosts.items():
            if isinstance(sub, dict) and "error" in sub \
                    and "version" not in sub:
                continue  # unreachable host's typed error stub
            for msg in validate_bundle(sub):
                failures.append(f"host {host}: {msg}")
        timeline = bundle.get("timeline")
        if not isinstance(timeline, list):
            failures.append("pod bundle timeline missing")
        else:
            last = None
            for i, ev in enumerate(timeline):
                key = (ev.get("ts", 0.0), ev.get("host", ""),
                       ev.get("seq", 0))
                if last is not None and key < last:
                    failures.append(f"timeline event {i} out of order")
                    break
                last = key
        return failures
    events = bundle.get("events")
    if not isinstance(events, list):
        failures.append("events missing or not a list")
        events = []
    prev = None
    for i, ev in enumerate(events):
        kind_ = ev.get("kind")
        spec = EVENT_SPECS.get(kind_)
        if spec is None:
            failures.append(f"event {i}: undeclared kind {kind_!r}")
            continue
        attrs = ev.get("attrs")
        if not isinstance(attrs, dict):
            failures.append(f"event {i} ({kind_}): attrs missing")
            continue
        extra = set(attrs) - set(spec[2])
        if extra:
            failures.append(f"event {i} ({kind_}): undeclared attrs "
                            f"{sorted(extra)}")
        if not isinstance(ev.get("ts"), (int, float)):
            failures.append(f"event {i} ({kind_}): bad ts")
        seq = ev.get("seq")
        if not isinstance(seq, int):
            failures.append(f"event {i} ({kind_}): bad seq")
        elif prev is not None and seq <= prev:
            failures.append(f"event {i} ({kind_}): seq not "
                            f"monotonic")
        else:
            prev = seq
    traces = bundle.get("traces")
    if not isinstance(traces, list):
        failures.append("traces missing or not a list")
        traces = []
    for i, tr in enumerate(traces):
        if not isinstance(tr.get("trace_id"), int):
            failures.append(f"trace {i}: bad trace_id")
        if tr.get("reason") not in ("error", "slow", "flagged"):
            failures.append(f"trace {i}: unknown retention reason "
                            f"{tr.get('reason')!r}")
        evs = tr.get("events")
        if not isinstance(evs, list):
            failures.append(f"trace {i}: events missing")
            continue
        for j, ev in enumerate(evs):
            if ev.get("ph") not in ("X", "i", "C"):
                failures.append(f"trace {i} event {j}: bad ph "
                                f"{ev.get('ph')!r}")
                break
    prom = bundle.get("prometheus")
    if isinstance(prom, str) and prom:
        from .exporters import parse_prometheus_text
        try:
            parse_prometheus_text(prom)
        except ValueError as exc:
            failures.append(f"prometheus snapshot invalid: {exc}")
    elif not bundle.get("prometheus_error"):
        failures.append("prometheus snapshot missing")
    cfg = bundle.get("config")
    if not isinstance(cfg, dict):
        failures.append("config section missing")
    elif "error" not in cfg:
        if not isinstance(cfg.get("knobs"), dict):
            failures.append("config knobs missing")
        if not isinstance(cfg.get("history"), list):
            failures.append("config history missing")
    if not isinstance(bundle.get("platform"), dict):
        failures.append("platform section missing")
    return failures


# ---------------------------------------------------------------------------
# overhead probe (the recorder_overhead bench row)
# ---------------------------------------------------------------------------

def overhead_probe(requests: int = 2000, repeats: int = 7,
                   stages: int = 4) -> Dict[str, float]:
    """Deterministic micro A/B of the serve hot path's recorder cost:
    each simulated request walks the executor's instrumentation
    checkpoints (``active()`` gate per stage, a ``RequestTrace`` with
    ``stages`` stage spans and the tail-retention close hook when
    armed) against a private tracer. Returns best-of-``repeats``
    per-request times in microseconds — min, not median: the probe
    measures the recorder's algorithmic cost, and on a loaded
    container every slow repeat is scheduler noise ADDED to that cost,
    so the minimum is the noise-immune statistic (medians swung 17-28
    us run-to-run under load). ``off_us`` is the recorder-disarmed
    path (the round-10 <= 1% budget: one module-global read per
    checkpoint), ``on_us`` the armed path (spans + holding-ring
    append), ``delta_us`` the gated difference."""
    from .trace import RequestTrace, active

    def run(on: bool) -> float:
        times = []
        tracer = Tracer(max_events=requests * (stages + 2))
        hook = _RETENTION.note_complete if on else None
        for _ in range(repeats):
            t0 = time.perf_counter()
            for _ in range(requests):
                if on:
                    tr = RequestTrace(tracer, "probe")
                    for s in range(stages):
                        tr.begin(f"stage{s}")
                        tr.finish(f"stage{s}")
                    # the close hook is what ships the tail
                    root = tr.root
                    tr.close()
                    if hook is not None and root is not None:
                        hook(tracer, root, "ok", None)
                else:
                    for _ in range(stages + 2):
                        if active():  # pragma: no cover - off by design
                            raise RuntimeError("probe expects tracing "
                                               "disabled")
            times.append(time.perf_counter() - t0)
            tracer.reset()
        return min(times) / requests * 1e6

    was_enabled = _trace.active()
    _trace.disable()
    try:
        off_us = run(False)
        on_us = run(True)
    finally:
        if was_enabled:
            _trace.enable()
        _RETENTION.reset()
    return {"off_us": off_us, "on_us": on_us,
            "delta_us": max(0.0, on_us - off_us),
            "requests": requests, "repeats": repeats}

"""Exporters: Chrome trace-event JSON and Prometheus text exposition.

Two machine-readable views over the same telemetry, chosen so a human
and a fleet scraper need zero knowledge of this codebase:

* :func:`export_trace` writes the Chrome trace-event format (the
  ``{"traceEvents": [...]}`` JSON that Perfetto and chrome://tracing
  open directly): one named track per pool device, one per priority
  lane, plus ``compile`` and ``exchange`` tracks; spans render as
  complete ("X") events carrying trace id / status / error in their
  args, annotations as instant ("i") events, per-chunk wire bytes as
  counter ("C") tracks.
* :func:`prometheus_text` renders the text exposition format
  (``# HELP`` / ``# TYPE`` + samples) over everything the process
  knows: the obs counter registry, a ``ServeMetrics`` snapshot, a
  ``PlanRegistry``'s stats, the ``timing.GlobalTimer`` call tree and
  the tracer's own lifecycle counters.
* :func:`parse_prometheus_text` is the minimal exposition-format
  parser the CI smoke round-trips the text through — if the output
  stops being valid exposition format, tier-1 goes red, not a scrape
  job three rounds later.
"""

from __future__ import annotations

import json
import re
from typing import Dict, List, Optional, Tuple

from .counters import GLOBAL_COUNTERS
from .trace import GLOBAL_TRACER, Span, Tracer


# -- Chrome trace-event JSON ------------------------------------------------

def trace_events(tracer: Optional[Tracer] = None,
                 events: Optional[List] = None,
                 bare: bool = False) -> List[dict]:
    """The tracer's buffer as a Chrome trace-event list. Tracks map to
    (pid=1, tid) rows with thread_name metadata; timestamps are
    microseconds since the tracer's epoch. ``events`` substitutes a
    pre-filtered raw slice of the buffer (the flight recorder converts
    one retained trace's events this way); ``bare`` omits the process/
    thread metadata events (sub-lists embedded in a bundle don't
    re-declare them)."""
    tracer = tracer or GLOBAL_TRACER
    raw = events if events is not None else tracer.events()
    tracks: Dict[str, int] = {}

    def tid(track: Optional[str]) -> int:
        name = track or "main"
        if name not in tracks:
            tracks[name] = len(tracks) + 1
        return tracks[name]

    def us(t: float) -> float:
        return round((t - tracer.epoch) * 1e6, 3)

    events: List[dict] = []
    for ev in raw:
        if isinstance(ev, Span):
            args = {"trace_id": ev.trace_id, "status": ev.status}
            if ev.parent_id is not None:
                args["parent_span_id"] = ev.parent_id
            args["span_id"] = ev.span_id
            if ev.error:
                args["error"] = ev.error
            if ev.args:
                args.update(ev.args)
            events.append({"ph": "X", "name": ev.name, "cat": ev.cat,
                           "ts": us(ev.t0),
                           "dur": round(ev.duration * 1e6, 3),
                           "pid": 1, "tid": tid(ev.track),
                           "args": args})
        elif ev.get("type") == "instant":
            args = dict(ev.get("args") or {})
            if ev.get("trace_id") is not None:
                args["trace_id"] = ev["trace_id"]
            events.append({"ph": "i", "s": "t", "name": ev["name"],
                           "cat": ev["cat"], "ts": us(ev["ts"]),
                           "pid": 1, "tid": tid(ev.get("track")),
                           "args": args})
        else:  # counter
            events.append({"ph": "C", "name": ev["name"],
                           "cat": ev["cat"], "ts": us(ev["ts"]),
                           "pid": 1, "tid": tid(ev.get("track")),
                           "args": ev.get("args") or {}})
    if bare:
        return events
    meta = [{"ph": "M", "pid": 1, "name": "process_name",
             "args": {"name": "spfft_tpu"}}]
    for name, t in sorted(tracks.items(), key=lambda kv: kv[1]):
        meta.append({"ph": "M", "pid": 1, "tid": t,
                     "name": "thread_name", "args": {"name": name}})
    return meta + events


def export_trace(path: str, tracer: Optional[Tracer] = None) -> dict:
    """Write the Chrome trace-event JSON to ``path`` (open it in
    Perfetto / chrome://tracing). Returns the payload dict."""
    tracer = tracer or GLOBAL_TRACER
    payload = {
        "traceEvents": trace_events(tracer),
        "displayTimeUnit": "ms",
        "otherData": {"producer": "spfft_tpu.obs",
                      "tracer": tracer.stats()},
    }
    with open(path, "w") as f:
        json.dump(payload, f)
    return payload


# -- Prometheus text exposition ---------------------------------------------

def _escape(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


class _PromBuilder:
    """Accumulates families in insertion order, one HELP/TYPE header per
    family, samples below it (the exposition-format grouping rule)."""

    def __init__(self):
        self._families: "Dict[str, Tuple[str, str, List[str]]]" = {}

    def add(self, name: str, mtype: str, help_: str,
            value: float, labels: Optional[dict] = None) -> None:
        fam = self._families.get(name)
        if fam is None:
            fam = self._families[name] = (mtype, help_, [])
        if labels:
            body = ",".join(f'{k}="{_escape(v)}"'
                            for k, v in sorted(labels.items()))
            series = f"{name}{{{body}}}"
        else:
            series = name
        fam[2].append(f"{series} {_format_value(value)}")

    def text(self) -> str:
        lines: List[str] = []
        for name, (mtype, help_, samples) in self._families.items():
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {mtype}")
            lines.extend(samples)
        return "\n".join(lines) + "\n"


def _format_value(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _serve_families(b: _PromBuilder, snap: dict) -> None:
    counters = [
        ("completed", "Requests completed successfully."),
        ("failed", "Requests resolved with an error."),
        ("rejected_queue_full", "Submits rejected by backpressure."),
        ("expired_deadline", "Requests expired before dispatch."),
        ("fused_batches", "Buckets dispatched through the fused path."),
        ("serial_batches", "Buckets dispatched serially."),
        ("padded_rows", "Ladder pad rows dispatched."),
        ("pinned_batches", "Buckets dispatched at a pinned shape."),
        ("fused_rows", "Live rows dispatched through fused buckets."),
    ]
    for key, help_ in counters:
        b.add(f"spfft_serve_{key}_total", "counter", help_,
              snap.get(key, 0))
    for cls, n in (snap.get("completed_by_class") or {}).items():
        b.add("spfft_serve_completed_by_class_total", "counter",
              "Completions per priority class.", n, {"class": cls})
    b.add("spfft_serve_queue_depth", "gauge",
          "Request queue depth at last enqueue/dequeue.",
          snap.get("queue_depth", 0))
    b.add("spfft_serve_max_queue_depth", "gauge",
          "High-water queue depth.", snap.get("max_queue_depth", 0))
    lat = snap.get("latency_seconds") or {}
    for q, v in lat.items():
        b.add("spfft_serve_latency_seconds", "gauge",
              "Request latency percentiles over the bounded reservoir.",
              v, {"quantile": q})
    for key, metric, help_ in (
            ("queue_wait_seconds", "spfft_serve_queue_wait_seconds",
             "Enqueue->dispatch wait percentiles (recent window) — "
             "the controller's queue-pressure signal."),
            ("device_execute_seconds",
             "spfft_serve_device_execute_seconds",
             "Dispatch->materialised bucket time percentiles (recent "
             "window) — the controller's device-cost signal.")):
        for q, v in (snap.get(key) or {}).items():
            b.add(metric, "gauge", help_, v, {"quantile": q})
    for cls, per in (snap.get("latency_seconds_by_class") or {}).items():
        for q, v in per.items():
            b.add("spfft_serve_latency_by_class_seconds", "gauge",
                  "Per-priority-class latency percentiles.", v,
                  {"class": cls, "quantile": q})
    for path, hkey in (("fused", "fused_batch_histogram"),
                       ("serial", "serial_batch_histogram")):
        for size, count in (snap.get(hkey) or {}).items():
            b.add("spfft_serve_batch_size_total", "counter",
                  "Dispatched buckets by live-row count and path.",
                  count, {"path": path, "size": size})
    overhead = snap.get("overhead_seconds") or {}
    for key in ("stage_total", "dispatch_total"):
        b.add("spfft_serve_overhead_seconds_total", "counter",
              "Host-side orchestration seconds.", overhead.get(key, 0.0),
              {"phase": key.replace("_total", "")})
    health = snap.get("health") or {}
    state = health.get("state")
    if state is not None:
        for s in ("healthy", "degraded", "draining", "failed"):
            b.add("spfft_serve_health", "gauge",
                  "Executor lifecycle state (one-hot).",
                  1 if s == state else 0, {"state": s})
    for key, value in health.items():
        if isinstance(value, (int, float)) and key != "state":
            b.add(f"spfft_serve_{key}_total", "counter",
                  f"Failure-handling counter: {key}.", value)
        elif isinstance(value, dict):
            for cls, n in value.items():
                if isinstance(n, (int, float)):
                    b.add(f"spfft_serve_{key}_total", "counter",
                          f"Failure-handling counter: {key}.", n,
                          {"class": cls})


def _registry_families(b: _PromBuilder, stats: dict) -> None:
    gauges = {"plans", "bytes_in_use", "max_bytes", "max_plans",
              "sig_memo_entries", "sig_memo_bytes", "hit_rate",
              "store_attached"}
    for key, value in stats.items():
        if not isinstance(value, (int, float)):
            continue
        if key in gauges:
            b.add(f"spfft_registry_{key}", "gauge",
                  f"Plan registry {key.replace('_', ' ')}.", value)
        else:
            b.add(f"spfft_registry_{key}_total", "counter",
                  f"Plan registry {key.replace('_', ' ')}.", value)


def _timing_families(b: _PromBuilder, timer) -> None:
    try:
        tree = json.loads(timer.process().json())
    except Exception:
        return

    def visit(node, prefix):
        scope = f"{prefix}/{node['label']}" if prefix else node["label"]
        b.add("spfft_timing_seconds_total", "counter",
              "Accumulated scope-timer seconds (timing.GlobalTimer).",
              node["total"], {"scope": scope})
        b.add("spfft_timing_calls_total", "counter",
              "Scope-timer call counts (timing.GlobalTimer).",
              node["count"], {"scope": scope})
        for sub in node.get("sub", ()):
            visit(sub, scope)

    for root in tree.get("timings", ()):
        visit(root, "")


def prometheus_text(metrics=None, registry=None, timer=None,
                    counters=None, tracer: Optional[Tracer] = None) -> str:
    """Render everything the process knows as Prometheus text
    exposition. All arguments optional: ``metrics`` is a
    ``ServeMetrics`` (or a pre-taken ``snapshot()`` dict), ``registry``
    a ``PlanRegistry``; ``timer`` defaults to ``timing.GlobalTimer``,
    ``counters``/``tracer`` to the obs globals."""
    b = _PromBuilder()
    counters = counters if counters is not None else GLOBAL_COUNTERS
    for name, fam in sorted(counters.snapshot().items()):
        for key, value in sorted(fam["samples"].items()):
            b.add(name, fam["type"], fam["help"], value, dict(key))
    if metrics is not None:
        snap = metrics if isinstance(metrics, dict) \
            else metrics.snapshot()
        _serve_families(b, snap)
        if registry is None and isinstance(snap.get("registry"), dict):
            _registry_families(b, snap["registry"])
    if registry is not None:
        stats = registry if isinstance(registry, dict) \
            else registry.stats()
        _registry_families(b, stats)
    if timer is None:
        from .. import timing
        timer = timing.GlobalTimer
    _timing_families(b, timer)
    tracer = tracer or GLOBAL_TRACER
    tstats = tracer.stats()
    b.add("spfft_trace_spans_started_total", "counter",
          "Spans begun since the tracer's last reset.",
          tstats["started"])
    b.add("spfft_trace_spans_closed_total", "counter",
          "Spans finished since the tracer's last reset.",
          tstats["closed"])
    b.add("spfft_trace_spans_open", "gauge",
          "Spans currently open (must be 0 at quiescence).",
          tstats["open"])
    b.add("spfft_trace_events_dropped_total", "counter",
          "Events dropped by the bounded ring buffer.",
          tstats["dropped"])
    return b.text()


# -- minimal exposition-format parser (the round-trip test) -----------------

_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>[^}]*)\})?'
    r'\s+(?P<value>[0-9eE+.\-]+|NaN|\+Inf|-Inf)\s*$')
_LABEL_PAIR_RE = re.compile(
    r'\s*(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:\\.|[^"\\])*)"\s*(?:,|$)')
_HELP_RE = re.compile(r"^# HELP (?P<name>\S+) (?P<help>.*)$")
_TYPE_RE = re.compile(
    r"^# TYPE (?P<name>\S+) "
    r"(?P<type>counter|gauge|histogram|summary|untyped)$")


def parse_prometheus_text(text: str) -> Dict[Tuple, float]:
    """Parse exposition-format text into ``{(name, ((label, value),
    ...)): float}``, VALIDATING as it goes: every sample line must
    match the format, every sampled metric must carry a prior ``# TYPE``
    declaration, and label pairs must be well-formed. Raises
    ``ValueError`` on any violation — this is the CI round-trip check,
    not a lenient scraper."""
    types: Dict[str, str] = {}
    out: Dict[Tuple, float] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            m = _TYPE_RE.match(line)
            if m:
                if m.group("name") in types:
                    raise ValueError(
                        f"line {lineno}: duplicate TYPE for "
                        f"{m.group('name')}")
                types[m.group("name")] = m.group("type")
                continue
            if _HELP_RE.match(line) or line.startswith("# "):
                continue
            raise ValueError(f"line {lineno}: bad comment {line!r}")
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: bad sample {line!r}")
        name = m.group("name")
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in types:
                base = name[:-len(suffix)]
                break
        if base not in types:
            raise ValueError(
                f"line {lineno}: sample {name!r} has no TYPE "
                f"declaration")
        labels: List[Tuple[str, str]] = []
        body = m.group("labels")
        if body:
            pos = 0
            while pos < len(body):
                lm = _LABEL_PAIR_RE.match(body, pos)
                if not lm:
                    raise ValueError(
                        f"line {lineno}: bad labels {body!r}")
                labels.append((lm.group("k"), lm.group("v")))
                pos = lm.end()
        key = (name, tuple(labels))
        if key in out:
            raise ValueError(f"line {lineno}: duplicate series {key}")
        out[key] = float(m.group("value"))
    return out

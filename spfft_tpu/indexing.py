"""Index planning: sparse frequency triplets -> z-stick tables.

Vectorised NumPy reimplementation of the semantics of the reference index
conversion (reference: src/compression/indices.hpp:120-186 ``convert_index_triplets``,
:49-55 ``to_storage_index``, :105-117 ``check_stick_duplicates``) and the local
half of the distribution plan (reference: src/parameters/parameters.cpp:143-180).

All planning is host-side NumPy: it runs once per plan, produces static index
tables, and those tables become device-resident constants of the jitted
transform — mirroring how the reference computes all indices at plan time and
never at execute time (SURVEY.md §3.1).

Conventions (identical to the reference):

* A "z-stick" is the set of all sparse values sharing an (x, y) index pair;
  sticks are keyed by ``x * dim_y + y`` and ordered ascending by that key
  (indices.hpp:152-165 uses an ordered map with the same key).
* Each value maps to the flat index ``stick_id * dim_z + z`` into the packed
  stick array (indices.hpp:168-176).
* Negative ("centered") indices map to storage via ``dim + index``
  (indices.hpp:49-55). Centered indexing is detected by any negative index
  (indices.hpp:129-135).
* Bounds (indices.hpp:137-149): for a dimension of size n, centered indices
  must lie in [floor(n/2) - n + 1, floor(n/2)], non-negative ones in [0, n-1];
  hermitian (R2C) transforms additionally require x in [0, floor(n/2)]
  (docs/source/details.rst "Real-To-Complex Transforms").
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from .errors import (DuplicateIndicesError, InvalidIndicesError,
                     InvalidParameterError)
from .types import TransformType


def to_storage_index(dim: int, index: np.ndarray) -> np.ndarray:
    """Map [-N, N) frequency indices to [0, N) storage indices
    (reference: indices.hpp:49-55)."""
    return np.where(index < 0, index + dim, index)


def _check_triplet_bounds(hermitian: bool, centered: bool,
                          dim_x: int, dim_y: int, dim_z: int,
                          x: np.ndarray, y: np.ndarray, z: np.ndarray) -> None:
    """Bounds validation, exactly as reference indices.hpp:137-149.

    Runs AFTER :func:`canonicalize_hermitian_triplets`, so hermitian sets
    reaching it always satisfy x >= 0 — the x < 0 half of a redundant
    (Gamma-style full-sphere) set has already been folded onto its
    conjugate mirror sticks."""
    max_x = (dim_x // 2 + 1 if (hermitian or centered) else dim_x) - 1
    max_y = (dim_y // 2 + 1 if centered else dim_y) - 1
    max_z = (dim_z // 2 + 1 if centered else dim_z) - 1
    min_x = 0 if hermitian else max_x - dim_x + 1
    min_y = max_y - dim_y + 1
    min_z = max_z - dim_z + 1
    if ((x < min_x).any() or (x > max_x).any()
            or (y < min_y).any() or (y > max_y).any()
            or (z < min_z).any() or (z > max_z).any()):
        raise InvalidIndicesError(
            f"index triplet out of bounds for dims ({dim_x},{dim_y},{dim_z}), "
            f"hermitian={hermitian}, centered={centered}")


def canonicalize_hermitian_triplets(dim_x: int, dim_y: int, dim_z: int,
                                    x: np.ndarray, y: np.ndarray,
                                    z: np.ndarray):
    """Fold the redundant x < 0 half of a hermitian frequency set onto
    its conjugate-mirror triplets (reference ``symmetry-GPU`` layer:
    F(-x,-y,-z) = conj(F(x,y,z)) for real fields, so a Gamma-style full
    sphere carries each value twice).

    Every triplet with x < 0 maps to (-x, -y, -z) with a per-value
    conjugate flag; the plan then stores, transforms, and — critically —
    EXCHANGES only the non-redundant stick set (the distributed wire
    halving), while the existing post-exchange completions
    (:func:`~spfft_tpu.ops.stages.complete_plane_hermitian` /
    ``complete_stick_hermitian``) and the implicit mirror half of the
    r2c x-stage matrices reconstruct the rest. Triplets with x >= 0 are
    untouched, so every previously-valid hermitian set builds a
    byte-identical plan.

    Returns ``(x, y, z, conj)`` with ``conj`` a boolean per-value mask
    (None when nothing was folded). The frequency negation keeps centered
    bounds except at the even-dimension edge -N/2, whose mirror +N/2 is
    the SAME storage index — normalised here so the bounds check (which
    rejects a user-supplied -N/2, matching the reference) still accepts
    the mirror of a valid edge value.
    """
    neg = x < 0
    if not neg.any():
        return x, y, z, None

    def mirror(v, dim):
        mv = np.where(neg, -v, v)
        return np.where(neg & (2 * v == dim), -(dim // 2), mv)

    return (np.where(neg, -x, x), mirror(y, dim_y), mirror(z, dim_z),
            neg)


def convert_index_triplets(hermitian: bool, dim_x: int, dim_y: int, dim_z: int,
                           triplets: np.ndarray):
    """Convert (n, 3) index triplets into per-value flat indices and the
    ordered unique stick-key list.

    Returns ``(value_indices, stick_keys, centered, conj)`` where
    ``value_indices[i] = stick_id(i) * dim_z + z_storage(i)``,
    ``stick_keys`` is the ascending list of unique ``x*dim_y + y`` keys,
    and ``conj`` is the per-value conjugate mask of
    :func:`canonicalize_hermitian_triplets` (None when no hermitian
    folding happened).

    Semantics of reference indices.hpp:120-186, vectorised; hermitian
    sets may additionally carry the redundant x < 0 half, which is
    canonicalised onto conjugate-mirror sticks first.
    """
    triplets = np.asarray(triplets)
    if triplets.ndim != 2 or triplets.shape[1] != 3:
        raise InvalidParameterError(
            f"expected (n, 3) index triplets, got shape {triplets.shape}")
    if not np.issubdtype(triplets.dtype, np.integer):
        raise InvalidParameterError(
            f"index triplets must be integers, got dtype {triplets.dtype}")
    n = triplets.shape[0]
    if n > dim_x * dim_y * dim_z:
        raise InvalidParameterError(
            "more frequency values than grid elements (indices.hpp:126-128)")

    x, y, z = (triplets[:, 0].astype(np.int64), triplets[:, 1].astype(np.int64),
               triplets[:, 2].astype(np.int64))
    centered = bool((triplets < 0).any())
    conj = None
    if hermitian and (x < 0).any():
        x, y, z, conj = canonicalize_hermitian_triplets(
            dim_x, dim_y, dim_z, x, y, z)
    else:
        # The native core predates hermitian folding (it rejects x < 0 for
        # hermitian, matching the reference) — only un-folded sets take it.
        from . import native
        res = native.plan_indices(hermitian, dim_x, dim_y, dim_z, triplets)
        if res is not None:
            return res + (None,)

    _check_triplet_bounds(hermitian, centered, dim_x, dim_y, dim_z, x, y, z)

    xs = to_storage_index(dim_x, x)
    ys = to_storage_index(dim_y, y)
    zs = to_storage_index(dim_z, z)

    keys = xs * dim_y + ys
    stick_keys, stick_ids = np.unique(keys, return_inverse=True)
    value_indices = stick_ids.astype(np.int64) * dim_z + zs
    return (value_indices.astype(np.int32), stick_keys.astype(np.int32),
            centered, conj)


def check_stick_duplicates(stick_keys_per_shard: Sequence[np.ndarray]) -> None:
    """Raise if any z-stick appears on more than one shard
    (reference: indices.hpp:105-117)."""
    all_keys = np.concatenate([np.asarray(k) for k in stick_keys_per_shard]) \
        if stick_keys_per_shard else np.empty(0, np.int32)
    if all_keys.size != np.unique(all_keys).size:
        raise DuplicateIndicesError(
            "z-stick (x,y) index owned by more than one shard")


@dataclasses.dataclass(frozen=True)
class IndexPlan:
    """Static index tables for one shard's sparse frequency set.

    The local analogue of the reference ``Parameters`` object
    (reference: src/parameters/parameters.hpp:48-156): everything a transform
    needs to gather/scatter sparse values and place sticks in the frequency
    grid, computed once at plan time.
    """

    transform_type: TransformType
    dim_x: int
    dim_y: int
    dim_z: int
    centered: bool
    #: per-value flat index ``stick_id * dim_z + z`` (indices.hpp:168-176)
    value_indices: np.ndarray
    #: ascending unique ``x*dim_y + y`` stick keys (indices.hpp:179-185)
    stick_keys: np.ndarray
    #: per-value conjugate mask from hermitian x < 0 folding
    #: (:func:`canonicalize_hermitian_triplets`), or None when the user's
    #: triplets were already non-redundant. Marked values are read and
    #: written through a conjugation: backward conjugates them before
    #: decompress, forward conjugates the compressed output.
    value_conj: Optional[np.ndarray] = None

    @property
    def num_values(self) -> int:
        return int(self.value_indices.shape[0])

    @property
    def num_sticks(self) -> int:
        return int(self.stick_keys.shape[0])

    @property
    def hermitian(self) -> bool:
        return self.transform_type == TransformType.R2C

    @property
    def dim_x_freq(self) -> int:
        """Frequency-domain x extent: ``dim_x//2 + 1`` for R2C
        (reference: parameters.cpp:49), else ``dim_x``."""
        return self.dim_x // 2 + 1 if self.hermitian else self.dim_x

    @property
    def stick_x(self) -> np.ndarray:
        """Storage x index of each stick."""
        return self.stick_keys // self.dim_y

    @property
    def stick_y(self) -> np.ndarray:
        """Storage y index of each stick."""
        return self.stick_keys % self.dim_y

    @property
    def scatter_cols(self) -> np.ndarray:
        """Column index of each stick in the x-innermost frequency plane
        ``(dim_y, dim_x_freq)`` flattened: ``y * dim_x_freq + x``.

        The reference keeps a y-innermost plane on host and x-innermost on GPU
        (execution_host.cpp:147-151 vs execution_gpu.cpp:85-86); this framework
        uses x-innermost everywhere so the space-domain output is directly in
        the user layout ``(z*Ny + y)*Nx + x`` (docs/source/details.rst
        "Indexing") with no final transpose.
        """
        return (self.stick_y * self.dim_x_freq + self.stick_x).astype(np.int32)

    @property
    def scatter_cols_t(self) -> np.ndarray:
        """Column index of each stick in the *y-innermost* frequency plane
        ``(dim_x_freq, dim_y)`` flattened: ``x * dim_y + y`` — which is
        exactly the stick key. The matmul-DFT pipeline keeps the plane
        grid transposed (planes, x, y) through the y-stage so both xy
        DFT axes contract on the minor dimension with a single transpose
        pair per round trip (ops/dft.py)."""
        return self.stick_keys.astype(np.int32)

    @property
    def col_inv_t(self) -> np.ndarray:
        """Inverse of :attr:`scatter_cols_t` (see :func:`inverse_col_map`)."""
        return inverse_col_map(self.scatter_cols_t,
                               self.dim_x_freq * self.dim_y,
                               self.num_sticks)

    @property
    def slot_src(self) -> np.ndarray:
        """Inverse value map for the gather-based decompress (see
        :func:`inverse_slot_map`)."""
        return inverse_slot_map(self.value_indices,
                                self.num_sticks * self.dim_z,
                                self.num_values)

    @property
    def col_inv(self) -> np.ndarray:
        """Inverse column map for the gather-based backward unpack (see
        :func:`inverse_col_map`)."""
        return inverse_col_map(self.scatter_cols,
                               self.dim_y * self.dim_x_freq,
                               self.num_sticks)

    @property
    def zero_stick_id(self) -> Optional[int]:
        """Position of the (x=0, y=0) stick, or None if absent — the stick that
        receives hermitian completion for R2C (reference: parameters.cpp:133-139)."""
        hits = np.nonzero(self.stick_keys == 0)[0]
        return int(hits[0]) if hits.size else None


def inverse_slot_map(value_indices: np.ndarray, num_slots: int,
                     num_values: int) -> np.ndarray:
    """Invert the value->slot map: ``src[slot] = value index feeding that
    slot``, sentinel ``num_values`` for empty slots.

    Turns the reference's decompress *scatter*
    (compression_host.hpp:76-93) into a TPU-friendly *gather*: XLA lowers
    arbitrary-index scatters on TPU to near-serial updates (~1s for 8.8M
    values on v5e), while the equivalent gather through this precomputed
    inverse runs an order of magnitude faster. If the same slot is named by
    several duplicate triplets, the last occurrence wins (the reference's
    scatter order is unspecified for duplicates).
    """
    from . import native
    out = native.inverse_map(value_indices, num_slots, num_values)
    if out is not None:
        return out
    src = np.full(num_slots, num_values, np.int32)
    src[value_indices] = np.arange(num_values, dtype=np.int32)
    return src


def inverse_col_map(scatter_cols: np.ndarray, num_cols: int,
                    num_sticks: int) -> np.ndarray:
    """Invert the stick->plane-column map: ``col_inv[c] = stick id at column
    c``, sentinel ``num_sticks`` for empty columns. Turns the backward
    unpack scatter (transpose_host.hpp:132-154) into a row gather."""
    from . import native
    out = native.inverse_map(scatter_cols, num_cols, num_sticks)
    if out is not None:
        return out
    col_inv = np.full(num_cols, num_sticks, np.int32)
    col_inv[scatter_cols] = np.arange(num_sticks, dtype=np.int32)
    return col_inv


def occupied_x_window(xs: np.ndarray, dim_x_freq: int,
                      allow_wrap: bool) -> tuple:
    """Minimal window ``[x0, x0 + w)`` (cyclic when ``allow_wrap``) covering
    the occupied storage-x columns — the analogue of the reference's
    unique-x-index collection that drives its y-FFT-over-non-empty-rows
    optimization (reference: execution_host.cpp:139-145; centered sets wrap
    x, so the minimal cover is cyclic, not linear).

    Returns ``(x0, w)`` with ``0 <= x0 < dim_x_freq`` and
    ``1 <= w <= dim_x_freq``; column ``x`` maps to sub-column
    ``(x - x0) % dim_x_freq`` (< w).
    """
    u = np.unique(np.asarray(xs, np.int64))
    if u.size == 0:
        return 0, 1
    if u.size == dim_x_freq:
        return 0, dim_x_freq
    if not allow_wrap:
        return int(u[0]), int(u[-1] - u[0] + 1)
    # Largest cyclic gap between consecutive occupied columns: the window
    # is its complement.
    gaps = np.diff(np.concatenate([u, [u[0] + dim_x_freq]]))
    g = int(np.argmax(gaps))
    x0 = int(u[(g + 1) % u.size])
    w = dim_x_freq - int(gaps[g]) + 1
    return x0, w


def window_sub_cols(cols: np.ndarray, dim_x_freq: int, x0: int,
                    w: int) -> np.ndarray:
    """Map full-plane columns ``y * dim_x_freq + x`` to occupied-window
    columns ``y * w + (x - x0) % dim_x_freq`` (see
    :func:`occupied_x_window`). Every split-x consumer (local plan,
    distributed tables, compact-exchange schedule) MUST use this one
    mapping so grid layout and exchange tables cannot desynchronise."""
    cols = np.asarray(cols, np.int64)
    return ((cols // dim_x_freq) * w
            + (cols % dim_x_freq - x0) % dim_x_freq).astype(np.int32)


#: Largest representable element count for any derived size product: the
#: C ABI and the index tables use 64-bit signed sizes, and per-value flat
#: indices ``stick_id * dim_z + z`` are built in int64 — products beyond
#: this overflow silently downstream, so construction fails loudly
#: instead (reference: grid_internal.cpp:122-134 range-checks dimension
#: products at construction and throws OverflowError).
MAX_SIZE_PRODUCT = 2 ** 62


def check_size_overflow(dim_x: int, dim_y: int, dim_z: int) -> None:
    """Raise :class:`~spfft_tpu.errors.OverflowError_` when any size
    product a plan derives (grid elements, interleaved real count, padded
    stick slots) cannot be represented — at construction, matching the
    reference's check placement (grid_internal.cpp:122-134)."""
    from .errors import OverflowError_
    if int(dim_x) > 2 ** 31 - 1 or int(dim_y) > 2 ** 31 - 1 \
            or int(dim_z) > 2 ** 31 - 1:
        raise OverflowError_(
            f"dimension exceeds 32-bit index range "
            f"({dim_x},{dim_y},{dim_z})")
    if 2 * int(dim_x) * int(dim_y) * int(dim_z) > MAX_SIZE_PRODUCT:
        raise OverflowError_(
            f"grid size product 2*{dim_x}*{dim_y}*{dim_z} overflows the "
            f"64-bit size range")
    # The per-plane gather tables (stick keys x*dim_y+y, col_inv over
    # dim_x_freq*dim_y columns) are int32; a plane bigger than int32 would
    # wrap them silently (round-4 advisor finding), so fail loudly here.
    if int(dim_x) * int(dim_y) > 2 ** 31 - 1:
        raise OverflowError_(
            f"plane size {dim_x}*{dim_y} exceeds the int32 range of the "
            f"stick-key/column gather tables")


def build_index_plan(transform_type: TransformType,
                     dim_x: int, dim_y: int, dim_z: int,
                     triplets: np.ndarray) -> IndexPlan:
    """Build the index plan for one shard's triplet list.

    Dimension/parameter validation mirrors reference grid_internal.cpp:122-145
    and transform_internal.cpp:52-83.
    """
    if dim_x < 1 or dim_y < 1 or dim_z < 1:
        raise InvalidParameterError(
            f"dimensions must be >= 1, got ({dim_x},{dim_y},{dim_z})")
    check_size_overflow(dim_x, dim_y, dim_z)
    transform_type = TransformType(transform_type)
    hermitian = transform_type == TransformType.R2C
    value_indices, stick_keys, centered, value_conj = convert_index_triplets(
        hermitian, dim_x, dim_y, dim_z, triplets)
    # Stick-slot space and per-value flat indices are int32 tables
    # (value_indices, slot_src); num_sticks is known only after the
    # unique() above, so the int32-range check lives here rather than in
    # check_size_overflow (round-4 advisor finding: a sparse
    # 4096x4096x1024 plan passed the 2^62 guard and wrapped silently).
    from .errors import OverflowError_
    num_sticks = int(stick_keys.shape[0])
    if num_sticks * int(dim_z) > 2 ** 31 - 1 \
            or int(value_indices.shape[0]) > 2 ** 31 - 1:
        raise OverflowError_(
            f"stick-slot count {num_sticks}*{dim_z} (or value count "
            f"{value_indices.shape[0]}) exceeds the int32 range of the "
            f"compression gather tables")
    return IndexPlan(transform_type=transform_type, dim_x=dim_x, dim_y=dim_y,
                     dim_z=dim_z, centered=centered,
                     value_indices=value_indices, stick_keys=stick_keys,
                     value_conj=value_conj)

"""Benchmark CLI: ``python -m spfft_tpu.benchmark``.

Rebuild of the reference benchmark program (reference:
tests/programs/benchmark.cpp) with the same knobs and output schema:

* workload: dense-within-cutoff stick set — all (x, y) sticks with
  ``x < dim_x_freq * sparsity``, full z sticks, split round-robin over
  shards when distributed (reference: benchmark.cpp:176-205);
* measurement: warm-up pass, then repeated backward+forward pairs
  (reference: benchmark.cpp:84-96), wall-clock with a hard device sync at
  the end of the timed loop;
* output: per-phase timing tree + JSON dump with ``timings`` and
  ``parameters`` sections (reference: benchmark.cpp:276-308).

Flags mirror reference benchmark.cpp:138-156: -d dims, -r repeats,
-s sparsity, -t c2c|r2c, -e exchange, -p host|device, -m num transforms,
-o json output; plus --shards to run distributed over a device mesh,
--precision for the float twin, and --fused/--no-fused to A/B the fused
compression+z-DFT Pallas path (docs/kernels.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np


def cutoff_stick_triplets(dim_x: int, dim_y: int, dim_z: int,
                          sparsity: float, hermitian: bool) -> np.ndarray:
    """Dense-within-cutoff stick set (reference: benchmark.cpp:176-205):
    every (x, y) stick with x below ``dim_x_freq * sparsity``, full z."""
    dim_x_freq = dim_x // 2 + 1 if hermitian else dim_x
    num_x = max(1, min(dim_x_freq, int(round(dim_x_freq * sparsity))))
    x = np.arange(num_x, dtype=np.int32)
    y = np.arange(dim_y, dtype=np.int32)
    z = np.arange(dim_z, dtype=np.int32)
    X, Y, Z = np.meshgrid(x, y, z, indexing="ij")
    return np.stack([X.ravel(), Y.ravel(), Z.ravel()], axis=1)


def _parse_args(argv):
    p = argparse.ArgumentParser(
        prog="python -m spfft_tpu.benchmark",
        description="spfft_tpu benchmark (reference: tests/programs/"
                    "benchmark.cpp)")
    p.add_argument("-d", "--dimensions", type=int, nargs="+", required=True,
                   metavar="N", help="grid dims: one value (cubic) or three")
    p.add_argument("-r", "--repeats", type=int, default=10)
    p.add_argument("-w", "--warmups", type=int, default=1)
    p.add_argument("-s", "--sparsity", type=float, default=1.0,
                   help="fraction of x range covered by sticks (default 1)")
    p.add_argument("-t", "--transform", choices=["c2c", "r2c"],
                   default="c2c")
    p.add_argument("-e", "--exchange",
                   choices=["default", "buffered", "bufferedFloat",
                            "compact", "compactFloat", "unbuffered", "all"],
                   default="default",
                   help="'all' sweeps every exchange mechanism on one "
                        "workload and prints a comparison table with HLO "
                        "wire bytes (reference: benchmark.cpp:138-156)")
    p.add_argument("-p", "--proc", choices=["host", "device"],
                   default="device",
                   help="host: numpy I/O every repeat; device: arrays stay "
                        "resident (reference -p cpu|gpu|gpu-gpu)")
    p.add_argument("-m", "--num-transforms", type=int, default=1)
    p.add_argument("-o", "--output", default=None, metavar="FILE.json")
    p.add_argument("--fused-pair", action="store_true",
                   help="time backward+forward as ONE fused executable "
                        "(apply_pointwise identity; requires -m 1)")
    p.add_argument("--fused", dest="fused", action="store_true",
                   default=None,
                   help="force the fused compression+z-DFT Pallas "
                        "kernels on (ops/fused_kernel.py; implies "
                        "use_pallas=True). Off-TPU this also forces the "
                        "matmul-DFT pipeline and interpret-mode kernel "
                        "execution, so CPU A/B numbers vs --no-fused "
                        "are honest overhead-only (docs/kernels.md)")
    p.add_argument("--no-fused", dest="fused", action="store_false",
                   help="disable the fused compression+z-DFT path (the "
                        "two-kernel pipeline; the A/B twin of --fused)")
    p.add_argument("--serve", action="store_true",
                   help="route the -m transforms through the serving "
                        "layer (spfft_tpu.serve: registry + batching "
                        "executor) instead of multi_transform_*; local "
                        "plans only (requires --shards 1)")
    p.add_argument("--shards", type=int, default=1,
                   help="distribute over an N-device mesh (default local)")
    p.add_argument("--overlap-chunks", type=int, default=None,
                   metavar="K",
                   help="split the distributed exchange into K "
                        "destination-balanced chunks so the z/xy FFT "
                        "stages pipeline with the collectives "
                        "(parallel/overlap.py; default 1 = monolithic, "
                        "or SPFFT_TPU_OVERLAP_CHUNKS)")
    p.add_argument("--cpu", action="store_true",
                   help="force a virtual CPU platform with --shards devices "
                        "(multi-chip simulation, like the test conftest)")
    p.add_argument("--precision", choices=["single", "double"],
                   default="single")
    p.add_argument("--store-dir", default=None, metavar="DIR",
                   help="cold/warm plan-resolution A/B through the "
                        "persistent plan-artifact store "
                        "(spfft_tpu.serve.store): resolve this "
                        "workload's plan through a store-backed "
                        "registry in-process (cold when DIR starts "
                        "empty: build + async spill), then re-resolve "
                        "it in a FRESH subprocess (warm: artifact load, "
                        "zero builds). Adds cold_start_ms/warm_start_ms "
                        "to the JSON; use a fresh DIR per honest A/B "
                        "(docs/artifact_cache.md)")
    p.add_argument("--profile-dir", default=None, metavar="DIR",
                   help="capture a jax.profiler trace of the measured "
                        "window into DIR — the pipeline's "
                        "jax.named_scope phase names (decompress/z/"
                        "exchange/xy) become visible in the device "
                        "profile (open with TensorBoard/XProf)")
    args = p.parse_args(argv)
    if args.fused_pair and args.num_transforms != 1:
        p.error("--fused-pair requires -m 1")
    if args.serve and (args.shards > 1 or args.fused_pair):
        p.error("--serve requires --shards 1 and no --fused-pair")
    if args.store_dir and args.shards > 1:
        p.error("--store-dir measures local plan resolution "
                "(requires --shards 1)")
    return args


_EXCHANGE = {
    "default": "default", "buffered": "buffered",
    "bufferedFloat": "buffered_float", "compact": "compact_buffered",
    "compactFloat": "compact_buffered_float", "unbuffered": "unbuffered",
}


def _exchange_sweep(args, dims, ttype, triplets, rng, cdt) -> int:
    """-e all: one workload, every exchange mechanism (reference:
    benchmark.cpp:138-156 runs the benchmark once per exchange for
    'all'). Prints a comparison table — pair wall-clock plus the
    aggregate and busiest-link wire bytes of the LOWERED exchange HLO —
    and writes the same rows into the -o JSON."""
    import jax
    from .parallel import make_distributed_plan, make_mesh
    from .types import ExchangeType
    from .utils.workloads import (even_plane_split,
                                  round_robin_stick_partition)

    nx, ny, nz = dims
    parts = round_robin_stick_partition(triplets, dims, args.shards)
    planes = even_plane_split(nz, args.shards)
    values_np = [
        (rng.uniform(-1, 1, len(p)) + 1j * rng.uniform(-1, 1, len(p)))
        .astype(cdt) for p in parts]
    variants = ["buffered", "bufferedFloat", "compact", "compactFloat",
                "unbuffered"]
    rows = []
    for name in variants:
        plan = make_distributed_plan(
            ttype, nx, ny, nz, parts, planes, mesh=make_mesh(args.shards),
            precision=args.precision,
            exchange=ExchangeType(_EXCHANGE[name]),
            overlap_chunks=args.overlap_chunks)
        values = plan.shard_values(values_np)
        last = None
        for _ in range(max(args.warmups, 1)):
            last = plan.apply_pointwise(values)
        jax.block_until_ready(last)
        np.asarray(jax.tree_util.tree_leaves(last)[-1]).ravel()[:1]
        t0 = time.perf_counter()
        for _ in range(args.repeats):
            out = plan.apply_pointwise(values)
        jax.block_until_ready(out)
        np.asarray(jax.tree_util.tree_leaves(out)[-1]).ravel()[:1]
        pair_s = (time.perf_counter() - t0) / args.repeats
        # Hermitian trimming state, disclosed per row: an r2c plan's
        # exchange ships only the non-redundant stick set, so its wire
        # column is NOT comparable against a c2c (untrimmed) sweep of
        # the same sphere without this tag (docs/distributed.md).
        folded = sum(int(sp.value_conj.sum())
                     for sp in plan.dist_plan.shard_plans
                     if sp.value_conj is not None)
        rows.append({
            "exchange": name,
            "overlap_chunks": plan.overlap_chunks,
            "pair_seconds": round(pair_s, 6),
            "wire_total_bytes": int(plan.exchange_wire_bytes()),
            "busiest_link_bytes": int(plan.exchange_busiest_link_bytes()),
            "hermitian_trimmed": bool(plan.dist_plan.hermitian),
            "folded_mirror_values": folded,
        })
    hdr = (f"{'exchange':>14s} {'pair ms':>10s} {'wire total MB':>14s} "
           f"{'busiest link MB':>16s} {'stick set':>18s}")
    print(hdr)
    for r in rows:
        trim = ("r2c-trimmed" + (f"(+{r['folded_mirror_values']}f)"
                                 if r["folded_mirror_values"] else "")
                if r["hermitian_trimmed"] else "untrimmed")
        print(f"{r['exchange']:>14s} {r['pair_seconds'] * 1e3:10.3f} "
              f"{r['wire_total_bytes'] / 1e6:14.3f} "
              f"{r['busiest_link_bytes'] / 1e6:16.3f} {trim:>18s}")
    if args.output:
        payload = {
            "parameters": {
                "dim_x": nx, "dim_y": ny, "dim_z": nz,
                "shards": args.shards, "sparsity": args.sparsity,
                "transform_type": args.transform,
                "precision": args.precision, "repeats": args.repeats,
                "backend": jax.default_backend(),
                "num_values": int(len(triplets)),
            },
            "exchange_sweep": rows,
        }
        with open(args.output, "w") as f:
            json.dump(payload, f, indent=2)
        print(f"wrote {args.output}")
    return 0


def _store_cold_warm(args, ttype, dims, triplets) -> dict:
    """The --store-dir A/B: resolve this workload's plan + first
    execution through a store-backed registry in-process (a true COLD
    start when the store directory begins empty — build, spill), then
    measure the WARM boot in a genuinely fresh interpreter (``python -m
    spfft_tpu.serve.store prewarm --compile``: artifact load + first
    execution, builds == 0). Returns the cold_start_ms/warm_start_ms
    pair BENCH_r06.json records and scripts/bench_regress.py compares
    from round 13 on."""
    import subprocess

    from .serve.registry import PlanRegistry
    from .serve.store import PlanArtifactStore

    store = PlanArtifactStore(args.store_dir)
    reg = PlanRegistry(store=store)
    t0 = time.perf_counter()
    sig, plan = reg.get_or_build(ttype, *dims, triplets,
                                 precision=args.precision)
    n = plan.index_plan.num_values
    plan.backward(np.zeros((n, 2), np.float32)
                  if plan.precision == "single"
                  else np.zeros(n, np.complex128))
    cold_ms = (time.perf_counter() - t0) * 1e3
    store.drain()
    out = {
        "store_dir": args.store_dir,
        # a pre-populated DIR makes the in-process number a warm one;
        # disclose rather than silently mislabel
        "store_was_cold": reg.stats()["builds"] == 1,
        "cold_start_ms": {"value": round(cold_ms, 3), "unit": "ms",
                          "metric": "plan resolve + first execute, "
                                    "empty store (build + spill)"},
    }
    proc = subprocess.run(
        [sys.executable, "-m", "spfft_tpu.serve.store", "prewarm",
         args.store_dir, "--compile", "--strict", "--json"],
        capture_output=True, text=True, timeout=1800)
    if proc.returncode != 0:
        print(f"warning: warm-boot subprocess failed:\n{proc.stderr}",
              file=sys.stderr)
        return out
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    out["warm_start_ms"] = {
        "value": report["warm_resolve_ms"], "unit": "ms",
        "metric": "plan resolve + first execute, fresh process over "
                  "the populated store (artifact load, builds==0)"}
    out["warm_builds"] = report["builds"]
    out["warm_store"] = report["store"]
    return out


def main(argv=None) -> int:
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    if args.cpu:
        from .utils.platform import force_virtual_cpu_devices
        force_virtual_cpu_devices(max(args.shards, 1))
    restore = {}
    if args.fused is not None:
        import jax

        def _setenv(key, value):
            restore.setdefault(key, os.environ.get(key))
            os.environ[key] = value

        _setenv("SPFFT_TPU_FUSED_COMPRESS", "1" if args.fused else "0")
        if args.fused and jax.default_backend() != "tpu":
            # the fused seam only exists in the matmul-DFT pipeline and
            # off-TPU the kernels execute in interpret mode: the CPU A/B
            # lane measures honest orchestration overhead only
            # (docs/kernels.md)
            _setenv("SPFFT_TPU_FORCE_MATMUL_DFT", "1")
            _setenv("SPFFT_TPU_FUSED_INTERPRET", "1")
    try:
        return _run(args)
    finally:
        for key, value in restore.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value


def _run(args) -> int:
    dims = args.dimensions
    if len(dims) == 1:
        dims = dims * 3
    if len(dims) != 3:
        print("error: -d takes one or three values", file=sys.stderr)
        return 2
    if args.num_transforms < 1:
        print("error: -m must be >= 1", file=sys.stderr)
        return 2
    nx, ny, nz = dims

    import jax
    from . import timing
    from .grid import Transform
    from .plan import make_local_plan
    from .parallel import make_distributed_plan, make_mesh
    from .multi import multi_transform_backward, multi_transform_forward
    from .types import ExchangeType, Scaling, TransformType
    from .utils.dtypes import as_interleaved
    from .utils.workloads import (even_plane_split,
                                  round_robin_stick_partition)

    ttype = TransformType.C2C if args.transform == "c2c" else TransformType.R2C
    hermitian = ttype == TransformType.R2C
    triplets = cutoff_stick_triplets(nx, ny, nz, args.sparsity, hermitian)
    rng = np.random.default_rng(42)
    cdt = np.complex64 if args.precision == "single" else np.complex128

    if args.exchange == "all":
        if args.shards < 2:
            print("error: -e all compares exchange mechanisms and needs "
                  "--shards > 1", file=sys.stderr)
            return 2
        return _exchange_sweep(args, (nx, ny, nz), ttype, triplets, rng,
                               cdt)
    exchange = ExchangeType(_EXCHANGE[args.exchange])

    t0 = time.perf_counter()
    if args.shards > 1:
        if len(jax.devices()) < args.shards:
            print(f"error: {args.shards} shards but only "
                  f"{len(jax.devices())} devices", file=sys.stderr)
            return 2
        parts = round_robin_stick_partition(triplets, dims, args.shards)
        planes = even_plane_split(nz, args.shards)
        plan = make_distributed_plan(ttype, nx, ny, nz, parts, planes,
                                     mesh=make_mesh(args.shards),
                                     precision=args.precision,
                                     exchange=exchange,
                                     overlap_chunks=args.overlap_chunks,
                                     use_pallas=True if args.fused
                                     else None)
        values_np = [
            (rng.uniform(-1, 1, len(p)) + 1j * rng.uniform(-1, 1, len(p)))
            .astype(cdt) for p in parts]
        values = plan.shard_values(values_np)
    else:
        plan = make_local_plan(ttype, nx, ny, nz, triplets,
                               precision=args.precision,
                               use_pallas=True if args.fused else None)
        n = len(triplets)
        v = (rng.uniform(-1, 1, n) + 1j * rng.uniform(-1, 1, n)).astype(cdt)
        values_np = np.asarray(as_interleaved(v, args.precision))
        values = jax.device_put(values_np)
    plan_s = time.perf_counter() - t0

    transforms = [Transform(plan) for _ in range(args.num_transforms)]
    m = args.num_transforms

    serve_executor = None
    if args.serve:
        # the serving layer over the SAME plan: the registry is seeded
        # with the already-built plan and each repeat submits one
        # backward + one forward request per transform — the executor's
        # same-signature bucketing turns each phase into fused batches
        from .serve import PlanRegistry, PlanSignature, ServeExecutor
        registry = PlanRegistry()
        serve_sig = PlanSignature.of_plan(plan)
        registry.put(serve_sig, plan)
        serve_executor = ServeExecutor(registry)
        # every repeat submits exactly m same-signature requests, so the
        # adaptive observer pins the exact shape m after a few repeats —
        # prewarm it alongside the pow2 ladder to keep that compile out
        # of the measured loop
        serve_executor.prewarm(serve_sig, batch_sizes=(m,))

        def run_pair(vals):
            spaces = [f.result() for f in
                      [serve_executor.submit(serve_sig, vals)
                       for _ in range(m)]]
            outs = [f.result() for f in
                    [serve_executor.submit(serve_sig, s, "forward")
                     for s in spaces]]
            return outs
    elif args.fused_pair:
        def run_pair(vals):
            # one executable for backward+forward (apply_pointwise with
            # the identity operator) — the layout bench.py measures
            return plan.apply_pointwise(vals)
    else:
        def run_pair(vals):
            spaces = multi_transform_backward(transforms, [vals] * m)
            outs = multi_transform_forward(transforms, spaces,
                                           [Scaling.NONE] * m)
            return outs

    def sync(arrs):
        jax.block_until_ready(arrs)
        # Hard sync: a host readback defeats any queue-ahead on
        # remote-attached devices (device programs execute FIFO per core).
        np.asarray(jax.tree_util.tree_leaves(arrs)[-1]).ravel()[:1]

    if args.repeats < 1 or args.warmups < 0:
        print("error: -r must be >= 1 and -w >= 0", file=sys.stderr)
        return 2
    host_io = args.proc == "host"
    feed = values_np if host_io else values

    def read_back(arrs):
        # host mode round-trips results to numpy inside the timed loop, so
        # both transfer directions are measured (reference -p cpu semantics)
        for a in jax.tree_util.tree_leaves(arrs):
            np.asarray(a)

    for _ in range(args.warmups):
        last = run_pair(feed)
    if args.warmups:
        sync(last)

    profiling = False
    if args.profile_dir:
        try:
            jax.profiler.start_trace(args.profile_dir)
            profiling = True
        except Exception as exc:
            print(f"warning: jax.profiler capture unavailable: {exc}",
                  file=sys.stderr)
    timing.enable()
    timing.GlobalTimer.reset()
    t0 = time.perf_counter()
    for _ in range(args.repeats):
        outs = run_pair(feed)
        if host_io:
            read_back(outs)
    sync(outs)
    total = time.perf_counter() - t0
    timing.disable()
    if profiling:
        try:
            jax.profiler.stop_trace()
            print(f"wrote jax.profiler trace to {args.profile_dir}",
                  file=sys.stderr)
        except Exception as exc:
            print(f"warning: jax.profiler stop failed: {exc}",
                  file=sys.stderr)

    pair_s = total / args.repeats
    result = timing.GlobalTimer.process()
    params = {
        "proc": args.proc, "shards": args.shards,
        "devices": len(jax.devices()), "backend": jax.default_backend(),
        "dim_x": nx, "dim_y": ny, "dim_z": nz,
        "exchange": args.exchange, "repeats": args.repeats,
        "overlap_chunks": int(getattr(plan, "overlap_chunks", 1)),
        "transform_type": args.transform, "num_transforms": m,
        "fused_pair": bool(args.fused_pair),
        "sparsity": args.sparsity, "precision": args.precision,
        "num_values": int(len(triplets)),
        "pallas": bool(getattr(plan, "_pallas_active", False)
                       or getattr(plan, "_pallas_dist", None) is not None),
        "fused": bool(getattr(plan, "fused_active", False)),
        "fused_fallback": dict(getattr(plan, "fused_fallback_reasons",
                                       None) or {}),
        # distributed fused twins (both directions), with the decline or
        # inactive:<why> reason disclosed per direction — the --fused
        # --overlap-chunks crossed A/B reads these to explain a seam
        # that did not engage
        "fused_dist": bool(getattr(plan, "fused_dist_active", False)),
        "fused_dist_fallback": {
            k: v for k, v in
            (("bwd", getattr(plan, "fused_dist_fallback_reason", None)),
             ("fwd", getattr(plan, "fused_dist_fwd_fallback_reason",
                             None)))
            if v is not None},
        "plan_seconds": round(plan_s, 4),
        "pair_seconds": round(pair_s, 6),
    }
    if serve_executor is not None:
        serve_executor.close()
        params["serve"] = serve_executor.metrics.snapshot(
            serve_executor.registry)
    if args.store_dir:
        params.update(_store_cold_warm(args, ttype, (nx, ny, nz),
                                       triplets))
    print(json.dumps(params, indent=2))
    result.print()
    if args.output:
        payload = json.loads(result.json())
        payload["parameters"] = params
        with open(args.output, "w") as f:
            json.dump(payload, f, indent=2)
        print(f"wrote {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Batched execution of independent transforms with compute/comm overlap.

The reference's ``multi_transform_forward/backward`` interleaves the phases of
N transforms by hand — GPU kernels queued first, CPU transforms started, MPI
exchanges non-blocking, everything synchronised at the end (reference:
include/spfft/multi_transform.hpp, src/spfft/multi_transform_internal.hpp:47-145).

Under JAX the same overlap falls out of the asynchronous dispatch model: every
jitted call returns immediately with futures; XLA orders collectives and
compute per device queue and overlaps independent executions. So the batched
API here simply dispatches all transforms before blocking on any result —
preserving the reference's API shape and its overlap benefit without a
hand-written schedule.

The reference forbids transforms sharing a Grid in one batch because they
share scratch buffers (multi_transform_internal.hpp:52-59); plans here own no
mutable buffers, so any mix of transforms is legal.
"""

from __future__ import annotations

from typing import Optional, Sequence

from .errors import InvalidParameterError
from .grid import Transform
from .plan import TransformPlan
from .timing import suppressed, timed_transform
from .types import Scaling


def _check(transforms: Sequence[Transform], args: Sequence, what: str):
    if len(args) != len(transforms):
        raise InvalidParameterError(
            f"got {len(transforms)} transforms but {len(args)} {what}")


#: Local fusion threshold on the TOTAL batch work, B * grid elements —
#: re-measured round 3 with sync-cancelled timing
#: (scripts/measure_batch.py; the round-2 per-transform gate missed the B
#: dependence): 128^3 B=3 = 6.3M total fused wins 3.8x (0.73 vs 2.79 ms),
#: 128^3 B=8 = 16.8M loses 0.47x, 256^3 B=3 = 50M loses 0.60x. Below the
#: gate per-dispatch latency dominates and ONE fused executable wins;
#: above it device work dominates, async dispatch already pipelines the N
#: executions, and the vmapped pipeline is mildly less efficient.
FUSED_BATCH_MAX_GRID = 8_000_000

#: Distributed fusion threshold on the TOTAL per-shard batch work
#: (B * slab elements): the distributed path pays more per dispatch
#: (pack/exchange/unpack stages), so fusion stays profitable longer than
#: locally — measured round 3 (sync-cancelled, scripts/measure_batch.py):
#: 128^3 B=8 (16.8M total) fused wins 1.9x, 256^3 B=3 (50M) loses 0.64x.
#: PROVENANCE: those measurements ran comm_size=1 distributed plans (the
#: only configuration this container can time — one real chip); the
#: multi-shard economics (collective launch amortisation vs vmapped
#: exchange cost) are UNMEASURED. The gate's scaling behavior has a
#: structural check instead: tests/test_multi.py asserts the fused S=8
#: batch compiles ONE executable whose HLO stays sub-linear in B vs the
#: unfused N-dispatch path (wall-clock on a virtual CPU mesh would be
#: meaningless).
FUSED_BATCH_MAX_DIST_TOTAL = 32_000_000


def planned_batch_size(batch_size: int, cap: int) -> int:
    """The planned-batch pow2 ladder (the cuFFT idiom): the smallest
    power of two >= ``batch_size``, capped at ``cap``. Dispatching every
    bucket at a ladder size bounds the set of compiled batch shapes per
    plan to O(log cap) while wasting at most 2x compute on pad rows.
    Lives here, next to :func:`fusion_eligible`, because it is batching
    POLICY shared by the serving executor's fallback path and its
    prewarm — the adaptive pinning path (spfft_tpu.serve.executor)
    bypasses the ladder once a signature's batch size stabilises."""
    p = 2
    while p < batch_size and p < cap:
        p *= 2
    return min(p, cap)


def fusion_eligible(plan, batch_size: int) -> bool:
    """THE shared fusion gate: is a batch of ``batch_size`` transforms
    over ``plan`` in the regime where the fused executable wins? Local
    plans gate on TOTAL batch work B * grid elements (round-3
    sync-cancelled measurements: 128^3 B=3 = 6.3M fused wins 3.8x,
    128^3 B=8 = 16.8M loses 0.47x, 256^3 B=3 = 50M loses 0.60x — the
    round-2 per-transform-size gate missed the B dependence);
    distributed plans on per-shard slab work (see
    FUSED_BATCH_MAX_DIST_TOTAL). Shared by :func:`_shared_plan` and the
    serving executor (spfft_tpu.serve.executor), so the batching policy
    cannot drift between the two entry points."""
    if batch_size < 2:
        return False
    if isinstance(plan, TransformPlan):
        return batch_size * plan.global_size <= FUSED_BATCH_MAX_GRID
    dp = plan.dist_plan
    slab = dp.dim_x * dp.dim_y * dp.max_planes  # per-shard slab
    return batch_size * slab <= FUSED_BATCH_MAX_DIST_TOTAL


def _shared_plan(transforms: Sequence[Transform]):
    """If every transform wraps the *same* plan object (clones share their
    plan) AND the batch is in the regime where fusion wins
    (:func:`fusion_eligible`), return it — the batch then runs as ONE
    fused executable (local: vmapped + batched-grid kernel; distributed:
    one SPMD program with a per-shard batch axis) instead of N
    dispatches. Returns None otherwise (per-transform async dispatch,
    which XLA pipelines per device queue)."""
    if len(transforms) < 2:
        return None
    plan = transforms[0].plan
    if any(t.plan is not plan for t in transforms[1:]):
        return None
    return plan if fusion_eligible(plan, len(transforms)) else None


def multi_transform_backward(transforms: Sequence[Transform],
                             values_batch: Sequence):
    """Backward-execute N independent transforms (reference:
    multi_transform.hpp:56-66). Returns the list of space-domain results;
    all dispatched before any host synchronisation."""
    _check(transforms, values_batch, "value arrays")
    # Per-transform timing would block between dispatches and serialise the
    # batch; time the whole batch as one scope instead.
    with timed_transform("multi_backward") as box:
        with suppressed():
            plan = _shared_plan(transforms)
            if plan is not None:
                stacked = plan.backward_batched(values_batch)
                if isinstance(plan, TransformPlan):
                    box.value = [stacked[i] for i in range(len(transforms))]
                else:  # distributed: (S, B, planes, ...)
                    box.value = [stacked[:, i]
                                 for i in range(len(transforms))]
                for t, s in zip(transforms, box.value):
                    t.set_space_domain_data(s)
            else:
                box.value = [t.backward(v)
                             for t, v in zip(transforms, values_batch)]
    return box.value


def multi_transform_forward(transforms: Sequence[Transform],
                            space_batch: Optional[Sequence] = None,
                            scalings: Optional[Sequence[Scaling]] = None):
    """Forward-execute N independent transforms (reference:
    multi_transform.hpp:37-53). ``space_batch`` defaults to each transform's
    stored space-domain data; ``scalings`` defaults to NONE."""
    if space_batch is None:
        space_batch = [None] * len(transforms)
    if scalings is None:
        scalings = [Scaling.NONE] * len(transforms)
    _check(transforms, space_batch, "space arrays")
    _check(transforms, scalings, "scalings")
    with timed_transform("multi_forward") as box:
        with suppressed():
            plan = _shared_plan(transforms)
            fused = plan is not None \
                and all(s is not None for s in space_batch) \
                and len(set(scalings)) == 1
            if fused:
                stacked = plan.forward_batched(space_batch,
                                               Scaling(scalings[0]))
                if isinstance(plan, TransformPlan):
                    box.value = [stacked[i] for i in range(len(transforms))]
                else:  # distributed: (S, B, mv, 2)
                    box.value = [stacked[:, i]
                                 for i in range(len(transforms))]
                for t, s in zip(transforms, space_batch):
                    t.set_space_domain_data(s)
            else:
                box.value = [t.forward(s, sc)
                             for t, s, sc in zip(transforms, space_batch,
                                                 scalings)]
    return box.value

"""Typed error taxonomy for spfft_tpu.

Mirrors the reference exception hierarchy and C error-code enum
(reference: include/spfft/exceptions.hpp:40-295, include/spfft/errors.h:33-126).
Where the reference distinguishes CUDA/ROCm ("GPU") failures, this framework
reports TPU/XLA device failures through the single :class:`DeviceError` branch —
XLA surfaces device problems as runtime errors on the jitted callable, so the
fine-grained GPU sub-errors (launch/copy/invalid-pointer/...) have no TPU
counterpart and are collapsed.
"""

from __future__ import annotations

import enum


class ErrorCode(enum.IntEnum):
    """Stable error codes, mirroring ``SpfftError`` (reference: errors.h:33-126).

    GPU-specific codes that have no TPU counterpart are kept for API parity so
    code written against the reference's enum can be migrated mechanically.
    """

    SUCCESS = 0
    UNKNOWN = 1
    INVALID_HANDLE = 2
    OVERFLOW = 3
    ALLOCATION = 4
    INVALID_PARAMETER = 5
    DUPLICATE_INDICES = 6
    INVALID_INDICES = 7
    DISTRIBUTED_SUPPORT = 8   # reference: SPFFT_MPI_SUPPORT_ERROR
    DISTRIBUTED = 9           # reference: SPFFT_MPI_ERROR
    PARAMETER_MISMATCH = 10   # reference: SPFFT_MPI_PARAMETER_MISMATCH_ERROR
    HOST_EXECUTION = 11
    FFT = 12                  # reference: SPFFT_FFTW_ERROR
    DEVICE = 13               # reference: SPFFT_GPU_ERROR
    DEVICE_PRECEDING = 14
    DEVICE_SUPPORT = 15
    DEVICE_ALLOCATION = 16
    DEVICE_LAUNCH = 17
    DEVICE_NO_DEVICE = 18
    DEVICE_INVALID_VALUE = 19
    DEVICE_INVALID_DEVICE_PTR = 20
    DEVICE_COPY = 21
    DEVICE_FFT = 22


class GenericError(Exception):
    """Base class for all spfft_tpu errors (reference: exceptions.hpp:40-47)."""

    code = ErrorCode.UNKNOWN

    def error_code(self) -> ErrorCode:
        return self.code


class OverflowError_(GenericError):
    """Integer overflow in size computation (reference: exceptions.hpp:50-59)."""

    code = ErrorCode.OVERFLOW


# errors: waived(API-parity class - reference SPFFT_ALLOCATION_ERROR; kept for mechanical migration)
class AllocationError(GenericError):
    """Failed buffer allocation (reference: exceptions.hpp:62-71)."""

    code = ErrorCode.ALLOCATION


class InvalidParameterError(GenericError):
    """Invalid parameter passed to a plan or transform
    (reference: exceptions.hpp:74-83)."""

    code = ErrorCode.INVALID_PARAMETER


class DuplicateIndicesError(GenericError):
    """Duplicate z-stick indices — typically a z-column owned by two shards
    (reference: exceptions.hpp:86-95, indices.hpp:105-117)."""

    code = ErrorCode.DUPLICATE_INDICES


class InvalidIndicesError(GenericError):
    """Frequency-domain index triplet out of bounds
    (reference: exceptions.hpp:98-107, indices.hpp:137-149)."""

    code = ErrorCode.INVALID_INDICES


# errors: waived(API-parity class - reference MPISupportError; local-only builds never raise it)
class DistributedSupportError(GenericError):
    """Distributed operation requested without a device mesh
    (reference: exceptions.hpp:110-121, MPISupportError)."""

    code = ErrorCode.DISTRIBUTED_SUPPORT


class DistributedError(GenericError):
    """Failure in a collective/distributed operation
    (reference: exceptions.hpp:124-131, MPIError)."""

    code = ErrorCode.DISTRIBUTED


class ParameterMismatchError(GenericError):
    """Plan parameters disagree across shards/hosts
    (reference: exceptions.hpp:134-145, MPIParameterMismatchError;
    cross-rank checks grid_internal.cpp:148-167, parameters.cpp:92-109)."""

    code = ErrorCode.PARAMETER_MISMATCH


class HostExecutionError(GenericError):
    """Failed execution on host (reference: exceptions.hpp:148-157)."""

    code = ErrorCode.HOST_EXECUTION


class TableBuildError(HostExecutionError):
    """The plan's BACKGROUND compression-table build raised off-thread
    (``TransformPlan._build_compression_tables``). Surfaced as this
    typed error on the first execution call and STICKY thereafter —
    never a silent fallback to the XLA path, never a raw foreign
    exception type. ``cause`` (also chained as ``__cause__``) carries
    the original exception."""

    def __init__(self, message: str, cause: BaseException = None):
        super().__init__(message)
        self.cause = cause
        if cause is not None:
            self.__cause__ = cause


class ServeError(HostExecutionError):
    """Base class of serving-layer failures (spfft_tpu.serve). The
    serving layer is host-side orchestration over compiled plans, so
    these report through the host-execution branch; no reference
    counterpart exists (SpFFT has no request-driven executor)."""


class QueueFullError(ServeError):
    """The serving executor's bounded request queue is full —
    backpressure is reject-with-error, never silent blocking, so
    overloaded callers fail fast instead of stacking unbounded latency."""


class DeadlineExpiredError(ServeError):
    """A request's deadline elapsed before the executor dispatched it;
    the work was never executed."""


class RetryExhaustedError(ServeError):
    """A request failed, was classified transient, and failed again on
    its one bounded retry. ``cause`` (also chained as ``__cause__``)
    carries the exception the final attempt raised — the serving layer
    never swallows the underlying failure, it wraps it so callers can
    tell "retried and still broken" from a first-shot permanent error."""

    def __init__(self, message: str, cause: BaseException = None):
        super().__init__(message)
        self.cause = cause
        if cause is not None:
            self.__cause__ = cause


class NoHealthyDeviceError(ServeError):
    """Every device in the executor's pool is quarantined and none is
    due for probation — there is nowhere to run the request. Mirrors the
    reference's no-device condition (SPFFT_NO_DEVICE_ERROR) at the
    serving layer."""

    code = ErrorCode.DEVICE_NO_DEVICE


class DistributedPlanUnsupportedError(ServeError):
    """A ``DistributedTransformPlan`` was submitted to a bare
    single-host ``ServeExecutor``. The executor's device pool, batching
    shards and staging buffers are built around LOCAL plans (one device
    per request); a distributed plan spans its own mesh and pins its
    own placement, so routing it through the pool is undefined — it is
    rejected at submit time instead of failing deep inside dispatch.
    ``serve.cluster.PodFrontend`` is the submit surface that DOES carry
    distributed-plan requests (it routes them to the pod-wide SPMD lane
    instead of a host's device pool). Reports through the
    distributed-support branch (reference SPFFT_MPI_SUPPORT_ERROR,
    exceptions.hpp:110-121)."""

    code = ErrorCode.DISTRIBUTED_SUPPORT


class ClusterError(ServeError):
    """Base class of pod-frontend failures (``spfft_tpu.serve.cluster``):
    routing, host-lane RPC and reconciliation problems report through
    this branch so pod callers can catch one type. Reports through the
    distributed branch (reference SPFFT_MPI_ERROR, exceptions.hpp:
    124-131) — a pod is this framework's communicator."""

    code = ErrorCode.DISTRIBUTED


class HostLaneError(ClusterError):
    """A host lane's RPC failed or the lane is marked dead. Transient
    and host-attributed: the frontend's routing policy treats the lane
    like the executor's quarantine ladder treats a device — route
    around it and degrade pod health, never hang the caller. ``host``
    carries the lane's descriptor name."""

    transient = True

    def __init__(self, message: str, host: str = None):
        super().__init__(message)
        self.host = host


class ClusterReconciliationError(ClusterError):
    """Pod reconciliation found hosts disagreeing — a plan-signature
    digest mismatch across lanes, or a lane that failed the
    ``parallel.multihost`` digest-validation collective. The pod
    refuses to route onto a split-brain plan set; mirrors the
    reference's cross-rank parameter checks (grid_internal.cpp:148-167)
    at the serving tier."""

    code = ErrorCode.PARAMETER_MISMATCH


class NetProtocolError(ClusterError):
    """A wire frame failed to parse: bad magic, version mismatch, a
    truncated header/payload, or a malformed typed record
    (``spfft_tpu.net.frame``). Transient from the pod's point of view —
    the frontend routes around the lane that produced it exactly like a
    dead transport — but typed separately so a protocol-version skew
    across a fleet shows up as itself, not as generic lane death."""

    transient = True


class StaleEpochError(ClusterError):
    """Epoch-fenced rejection: routed work carried a membership-view
    epoch older than the receiver's (``spfft_tpu.net.membership``) —
    the sender is acting on a stale view of the pod. Transient by
    design: the correct recovery is to refetch the view from the
    coordinator and retry with the fresh epoch, which the pod frontend
    does automatically. ``stale``/``current`` carry both epochs so the
    skew is visible in the error text."""

    transient = True

    def __init__(self, message: str, stale: int = None,
                 current: int = None):
        super().__init__(message)
        self.stale = stale
        self.current = current


class NetAuthError(ClusterError):
    """Wire-authentication failure: a frame's HMAC did not verify, an
    authenticated endpoint received an unauthenticated frame, or vice
    versa (``SPFFT_TPU_NET_SECRET`` mismatch across the pod; the frame
    version byte negotiates the authenticated protocol). PERMANENT —
    retrying with the same secret can never succeed, so the door
    rejects once, typed, instead of burning the failover ladder."""

    transient = False


class ExecutorCrashedError(ServeError):
    """The dispatch loop crashed unexpectedly and its supervisor
    exhausted the bounded restart budget; every queued and in-flight
    future was failed with this error instead of hanging forever."""


class ExecuteTimeoutError(ServeError):
    """A bucket's device execute exceeded the ``execute_timeout_ms``
    watchdog knob. The wedged ``block_until_ready`` is abandoned to a
    daemon thread and the bucket fails with this TYPED, transient,
    device-attributed error — feeding the existing retry + quarantine
    ladder instead of hanging the dispatch loop forever (the last
    "zero hangs" gap). ``transient``/``device_attributed`` are the
    attribute tags ``faults.is_transient`` / ``attributes_device``
    read first."""

    transient = True
    device_attributed = True


class PlanArtifactError(ServeError):
    """A plan artifact named by a warmup manifest could not be loaded
    (missing, rejected, or incompatible with the requested kwargs).
    Raised by strict manifest prewarm — a replacement process must not
    silently join the pool half-warm; the ad-hoc ``get_or_build`` path
    never raises this (a rejected artifact there falls back to a clean
    rebuild with the reason counted)."""


class BlobStoreError(ServeError):
    """A remote blob-tier operation failed (``spfft_tpu.net.blobstore``):
    the backing object store is unreachable, answered a non-OK status,
    or the local file backend hit an I/O error. The plan-artifact store
    treats it as a remote-tier miss (counted, never raised through a
    load) — the remote tier is an optimisation below the disk tier, not
    a correctness dependency."""


class FFTError(GenericError):
    """Failure inside the FFT backend (reference: exceptions.hpp:160-167,
    FFTWError; here: XLA Fft HLO)."""

    code = ErrorCode.FFT


class PrecisionContractError(FFTError):
    """A plan's PREDICTED relative error exceeds the accuracy bound the
    caller demanded (``max_rel_error=``): the configured precision cannot
    meet the contract, so construction fails loudly instead of returning
    silently-degraded results. Subclass of :class:`FFTError` (it is an
    FFT-accuracy failure; the reference's closest surface is the FFTW
    error, exceptions.hpp:160-167 — its f64-everywhere build never needs
    the distinction, docs/precision.md explains why this one does)."""


# errors: waived(API-parity class - reference InternalError; no internal-assert surface yet)
class InternalError(GenericError):
    """Internal consistency failure (reference: exceptions.hpp:170-177)."""

    code = ErrorCode.UNKNOWN


class DeviceError(GenericError):
    """TPU/XLA device-side failure (reference: exceptions.hpp:183-190,
    GPUError branch)."""

    code = ErrorCode.DEVICE


# errors: waived(API-parity class - reference GPUSupportError; XLA reports device absence itself)
class DeviceSupportError(DeviceError):
    """Device execution requested but no accelerator is available
    (reference: exceptions.hpp:193-204)."""

    code = ErrorCode.DEVICE_SUPPORT


# errors: waived(API-parity class - reference GPUAllocationError; XLA owns device allocation)
class DeviceAllocationError(DeviceError):
    """Failed allocation on device (reference: exceptions.hpp:221-230)."""

    code = ErrorCode.DEVICE_ALLOCATION


# errors: waived(API-parity class - reference GPUFFTError; XLA owns the device FFT path)
class DeviceFFTError(DeviceError):
    """Failure in the device FFT path (reference: exceptions.hpp:295-304)."""

    code = ErrorCode.DEVICE_FFT

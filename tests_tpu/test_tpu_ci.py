"""On-TPU regression net (make ci-tpu): the exact code paths the
CPU-pinned suite cannot exercise, each against a dense numpy oracle.

Coverage (round-4 verdict item 3 + advisor finding 3):
  * oracle matrix 32^3/64^3, C2C + R2C, centered + positive indexing
  * Pallas compression kernel forced on (real Mosaic codegen + DMA)
  * the segmented aliased-carry accumulate path (input/output aliasing
    semantics only real hardware honors — the interpreter keeps the
    concat path, so this was previously validated by hand-run probes
    only)
  * split-x (occupied-window xy stage), pair-IO (2, N) boundary,
    two-stage Cooley-Tukey long axis, repeated-backward stability,
    fused iterate_pointwise
  * the fused compression+z-DFT kernels (ops/fused_kernel.py) on real
    Mosaic: bit-exact vs the dense oracle and the unfused two-kernel
    path, plus --profile-dir evidence that the dense stick
    intermediate is gone from the device profile (docs/kernels.md)
"""

import numpy as np
import pytest

import spfft_tpu.plan as plan_mod
from spfft_tpu import Scaling, TransformType, make_local_plan
from spfft_tpu.ops import gather_kernel as gk
from spfft_tpu.utils.workloads import spherical_cutoff_triplets

TOL = 1e-6


def _values(n_values, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(n_values)
            + 1j * rng.standard_normal(n_values)).astype(np.complex64)


def _dense_c2c_oracle(triplets, vals, dims):
    nx, ny, nz = dims
    st = triplets.copy()
    for a, n in enumerate(dims):
        st[:, a] = np.where(st[:, a] < 0, st[:, a] + n, st[:, a])
    cube = np.zeros((nz, ny, nx), np.complex64)
    cube[st[:, 2], st[:, 1], st[:, 0]] = vals
    return np.fft.ifftn(cube) * cube.size


def _rel(got, want):
    return np.linalg.norm(got - want) / max(np.linalg.norm(want), 1e-30)


def _check_c2c(plan, triplets, n, seed=0):
    vals = _values(len(triplets), seed)
    space = np.asarray(plan.backward(vals))
    got = space[..., 0] + 1j * space[..., 1]
    oracle = _dense_c2c_oracle(triplets, vals, (n, n, n))
    assert _rel(got, oracle) < TOL
    out = np.asarray(plan.forward(space, Scaling.FULL))
    if plan.pair_values_io:
        out = out.T
    assert _rel(out[:, 0] + 1j * out[:, 1], vals) < TOL
    return space


@pytest.mark.parametrize("n", [32, 64])
@pytest.mark.parametrize("indexing", ["centered", "positive"])
def test_oracle_c2c(n, indexing):
    tr = spherical_cutoff_triplets(n, radius=n // 2 - 1)
    if indexing == "positive":
        tr = np.where(tr < 0, tr + n, tr)
    plan = make_local_plan(TransformType.C2C, n, n, n, tr,
                           precision="single")
    _check_c2c(plan, tr, n)


@pytest.mark.parametrize("n", [32, 64])
@pytest.mark.parametrize("indexing", ["centered", "positive"])
def test_oracle_r2c(n, indexing):
    rng = np.random.default_rng(1)
    field = rng.standard_normal((n, n, n)).astype(np.float32)
    freq = np.fft.fftn(field)
    half = []
    for x in range(n // 2 + 1):
        for y in range(n):
            for z in range(n):
                half.append((x, y, z))
    tr = np.asarray(half, np.int64)
    vals = freq[tr[:, 2], tr[:, 1], tr[:, 0]].astype(np.complex64)
    if indexing == "centered":
        tr = tr.copy()
        for a in (1, 2):
            tr[:, a] = np.where(tr[:, a] > n // 2, tr[:, a] - n, tr[:, a])
    plan = make_local_plan(TransformType.R2C, n, n, n, tr,
                           precision="single")
    space = np.asarray(plan.backward(vals))
    assert _rel(space, field * field.size) < TOL
    out = np.asarray(plan.forward(space, Scaling.FULL))
    assert _rel(out[:, 0] + 1j * out[:, 1], vals) < TOL


def test_pallas_kernel_forced():
    """The Mosaic windowed-gather kernel on real hardware (auto-gate
    would skip it below 200k values; forcing keeps this test fast)."""
    n = 64
    tr = spherical_cutoff_triplets(n)
    plan = make_local_plan(TransformType.C2C, n, n, n, tr,
                           precision="single", use_pallas=True)
    assert plan.pallas_active
    _check_c2c(plan, tr, n, seed=2)


def test_segmented_aliased_carry_accumulate(monkeypatch):
    """Segmented multi-launch gathers accumulate through pallas
    input/output aliasing on real hardware — semantics the interpreter
    does not honor, so only this lane can regression-test them
    (advisor r4 finding 3). Shrinking the launch limits forces many
    segments on a small plan; the result must still match both the
    dense oracle and the XLA-gather path."""
    # limit 2 at 32^3 segments BOTH table kinds (measured: decompress =
    # wide kernel, 2 segments; compress = narrow kernel, 14 segments)
    monkeypatch.setattr(gk, "SEG_CHUNK_LIMIT", 2)
    monkeypatch.setattr(gk, "WIDE_SEG_CHUNK_LIMIT", 2)
    n = 32
    tr = spherical_cutoff_triplets(n)
    plan = make_local_plan(TransformType.C2C, n, n, n, tr,
                           precision="single", use_pallas=True)
    assert plan.pallas_active
    box = plan._pallas
    assert all(t is not None and t.segs for t in box.values()), \
        "launch limits did not force segmentation on both directions"
    vals = _values(len(tr), 3)
    space = np.asarray(plan.backward(vals))
    got = space[..., 0] + 1j * space[..., 1]
    oracle = _dense_c2c_oracle(tr, vals, (n, n, n))
    assert _rel(got, oracle) < TOL
    # forward leg drives the segmented COMPRESS carry
    out = np.asarray(plan.forward(space, Scaling.FULL))
    assert _rel(out[:, 0] + 1j * out[:, 1], vals) < TOL
    # XLA-gather cross-check through the same plan tables
    import jax
    vil = plan._coerce_values(vals)
    xla = np.asarray(jax.jit(
        lambda v, t: plan._backward_impl(v, t, pallas=False))(
            vil, plan._tables))
    np.testing.assert_allclose(space, xla, atol=1e-5, rtol=1e-5)


def test_split_x_window():
    """Occupied-x-window xy stage (plan._split_x) on real hardware,
    wrapped window included (centered x in [-3, 3])."""
    n = 64
    tr = spherical_cutoff_triplets(n)
    tr = tr[np.abs(tr[:, 0]) <= 3]
    plan = make_local_plan(TransformType.C2C, n, n, n, tr,
                           precision="single")
    assert plan._split_x is not None
    _check_c2c(plan, tr, n, seed=4)


def test_pair_io_boundary(monkeypatch):
    """The planar (2, N) value boundary (default only >= 16M values) on
    a small plan: layout flip must be observable and exact."""
    monkeypatch.setattr(plan_mod, "PAIR_IO_THRESHOLD", 1)
    n = 32
    tr = spherical_cutoff_triplets(n)
    plan = make_local_plan(TransformType.C2C, n, n, n, tr,
                           precision="single")
    assert plan.pair_values_io
    vals = _values(len(tr), 5)
    out = plan.forward(plan.backward(vals), Scaling.FULL)
    assert out.shape == (2, len(tr))
    assert _rel(np.asarray(out)[0] + 1j * np.asarray(out)[1], vals) < TOL


def test_two_stage_long_axis():
    """768 = 24*32 z-axis through the two-stage Cooley-Tukey matmul
    path on real hardware."""
    nx, ny, nz = 16, 16, 768
    rng = np.random.default_rng(6)
    tr = np.stack([rng.integers(0, nx, 3000), rng.integers(0, ny, 3000),
                   rng.integers(0, nz, 3000)], axis=-1)
    tr = np.unique(tr, axis=0)
    plan = make_local_plan(TransformType.C2C, nx, ny, nz, tr,
                           precision="single")
    assert plan._use_mdft
    vals = _values(len(tr), 6)
    space = np.asarray(plan.backward(vals))
    got = space[..., 0] + 1j * space[..., 1]
    oracle = _dense_c2c_oracle(tr, vals, (nx, ny, nz))
    assert _rel(got, oracle) < TOL


def test_repeated_backward_is_stable():
    """Back-to-back backward executions must agree bit-for-bit (the
    reference's repeated-transform zeroing check, benchmark.cpp) —
    catches stale-buffer reuse on the device."""
    n = 32
    tr = spherical_cutoff_triplets(n)
    plan = make_local_plan(TransformType.C2C, n, n, n, tr,
                           precision="single")
    vals = _values(len(tr), 7)
    a = np.asarray(plan.backward(vals))
    b = np.asarray(plan.backward(vals))
    np.testing.assert_array_equal(a, b)


def test_iterate_pointwise_fused_scan():
    """lax.scan-fused round trips == sequential apply_pointwise."""
    n = 32
    tr = spherical_cutoff_triplets(n)
    plan = make_local_plan(TransformType.C2C, n, n, n, tr,
                           precision="single")
    vals = _values(len(tr), 8)
    it = np.asarray(plan.iterate_pointwise(vals, None, steps=2,
                                           scaling=Scaling.FULL))
    one = plan.apply_pointwise(vals, scaling=Scaling.FULL)
    two = np.asarray(plan.apply_pointwise(one, scaling=Scaling.FULL))
    np.testing.assert_allclose(it, two, atol=1e-6, rtol=1e-5)


def test_on_device_double():
    """The double-single (hi, lo) + exact-sliced-dot double mode on the
    real MXU (ops/dsdft.py): partial-dot exactness and TwoSum behavior
    are hardware properties the CPU run cannot certify. Round-5
    measured: 2.0e-14 (64^3) / 5.0e-14 (128^3) backward rel l2 vs the
    dense f64 oracle."""
    n = 32
    rng = np.random.default_rng(11)
    tr = spherical_cutoff_triplets(n)
    plan = make_local_plan(TransformType.C2C, n, n, n, tr,
                           precision="double")
    assert plan._ds, "on-device double must engage on the TPU backend"
    vals = (rng.standard_normal(len(tr))
            + 1j * rng.standard_normal(len(tr)))
    space = plan.backward(vals)
    assert space.dtype == np.float64
    got = space[..., 0] + 1j * space[..., 1]
    st = np.where(tr < 0, tr + n, tr)
    cube = np.zeros((n, n, n), np.complex128)
    cube[st[:, 2], st[:, 1], st[:, 0]] = vals
    oracle = np.fft.ifftn(cube) * cube.size
    rel = np.linalg.norm(got - oracle) / np.linalg.norm(oracle)
    assert rel < 2e-12, rel   # contract envelope 2e-11; measured 1e-14
    out = plan.forward(space, Scaling.FULL)
    gv = out[:, 0] + 1j * out[:, 1]
    rel = np.linalg.norm(gv - vals) / np.linalg.norm(vals)
    assert rel < 2e-12, rel


def test_on_device_double_r2c():
    """R2C on-device double on the real MXU: half-spectrum real
    matrices through the same exact-sliced machinery, zero-stick and
    x=0-plane completions on double-single channels."""
    n = 16
    rng = np.random.default_rng(12)
    field = rng.standard_normal((n, n, n))
    freq = np.fft.fftn(field)
    tr = np.asarray([(x, y, z) for x in range(n // 2 + 1)
                     for y in range(n) for z in range(n)
                     if not (x == 0 and y == 0 and z > n // 2)],
                    np.int64)
    vals = freq[tr[:, 2], tr[:, 1], tr[:, 0]]
    plan = make_local_plan(TransformType.R2C, n, n, n, tr,
                           precision="double")
    assert plan._ds
    space = plan.backward(vals)
    assert space.dtype == np.float64
    rel = (np.linalg.norm(space - field * field.size)
           / np.linalg.norm(field * field.size))
    assert rel < 2e-12, rel
    out = plan.forward(space, Scaling.FULL)
    gv = out[:, 0] + 1j * out[:, 1]
    rel = np.linalg.norm(gv - vals) / np.linalg.norm(vals)
    assert rel < 2e-12, rel


def test_fused_stage_matches_xla(monkeypatch):
    """Fused Pallas DFT-stage kernels (real Mosaic codegen, in-VMEM
    transpose, HIGHEST-precision dots) vs the SPFFT_TPU_FUSED_STAGE=0
    XLA pipeline: same plan, same values. The two paths differ only in
    rounding order, so agreement is ~1e-7-class."""
    n = 64
    tr = spherical_cutoff_triplets(n)
    vals = _values(len(tr), 6)
    # dft_kernel.enabled() reads the env at TRACE time and plans trace
    # lazily at first execution — so each plan must EXECUTE while its
    # intended setting is live, or both trace the same path.
    import jax

    def hlo(plan):
        # lowered under the CURRENT env — the engagement proof below
        vil = plan._coerce_values(vals)
        return jax.jit(plan._backward_impl).lower(
            vil, plan._tables_hot).as_text()

    plan_f = make_local_plan(TransformType.C2C, n, n, n, tr,
                             precision="single")
    a = np.asarray(plan_f.backward(vals))
    fa = np.asarray(plan_f.forward(a, Scaling.FULL))
    hlo_f = hlo(plan_f)
    monkeypatch.setenv("SPFFT_TPU_FUSED_STAGE", "0")
    plan_x = make_local_plan(TransformType.C2C, n, n, n, tr,
                             precision="single")
    b = np.asarray(plan_x.backward(vals))
    fb = np.asarray(plan_x.forward(b, Scaling.FULL))
    hlo_x = hlo(plan_x)
    monkeypatch.delenv("SPFFT_TPU_FUSED_STAGE")
    # prove the A/B engaged: the fused plan lowers to Pallas custom
    # calls, the env=0 plan to plain dots (at 64^3 the two paths agree
    # BIT-FOR-BIT — same 6-pass dot algorithm either way — so result
    # inequality cannot serve as the engagement check)
    assert "tpu_custom_call" in hlo_f
    assert "tpu_custom_call" not in hlo_x
    assert _rel(a, b) < 5e-6
    assert _rel(fa, fb) < 5e-6


def test_batched_vmap_over_fused_kernels():
    """backward_batched/forward_batched vmap the pipeline over a batch
    axis; with the fused DFT-stage kernels active this exercises JAX's
    Pallas batching rule on real hardware (the CPU suite falls back to
    the XLA stages before reaching it). Each batch element must match
    the unbatched call exactly — same program modulo the vmap dimension."""
    n = 64
    tr = spherical_cutoff_triplets(n)
    plan = make_local_plan(TransformType.C2C, n, n, n, tr,
                           precision="single")
    vals = [_values(len(tr), seed) for seed in (11, 12, 13)]
    batch = np.stack([np.asarray(plan._coerce_values(v)) for v in vals])
    out_b = np.asarray(plan.backward_batched(batch))
    for k, v in enumerate(vals):
        single = np.asarray(plan.backward(v))
        assert _rel(out_b[k], single) < 1e-6
    fwd_b = np.asarray(plan.forward_batched(out_b, Scaling.FULL))
    for k, v in enumerate(vals):
        got = fwd_b[k]
        assert _rel(got[:, 0] + 1j * got[:, 1], v) < TOL


def test_stage_kernel_compile_envelope():
    """The kernel tile chooser must pick configs that actually COMPILE
    on this chip: the formula-vs-Mosaic gap crashed 320^3/384^3 plans
    when the budget allowed 7-8 MB tiles (envelope regression, fixed by
    the 5.5 MB empirical ceiling). Compiles one stage at each larger
    axis class and the complex xy dispatcher on device."""
    import jax
    import jax.numpy as jnp
    from spfft_tpu.ops import dft, dft_kernel as dk

    rng = np.random.default_rng(30)
    for n in (384, 512):
        mats = dft.c2c_mats(n, dft.BACKWARD)
        xr = jnp.asarray(rng.standard_normal((1536, n)), jnp.float32)
        xi = jnp.asarray(rng.standard_normal((1536, n)), jnp.float32)
        yr, yi = jax.jit(
            lambda a, b, m=mats: dk.pdft_last(a, b, m))(xr, xi)
        got = np.asarray(yr, np.float64) + 1j * np.asarray(yi, np.float64)
        want = np.fft.ifft(np.asarray(xr, np.float64)
                           + 1j * np.asarray(xi, np.float64), axis=-1) * n
        assert _rel(got, want) < 1e-5

    # complex xy dispatcher (the distributed wrappers' fused path) at
    # n=64 (fast correctness) and at n=320 — the LARGEST eligible axis
    # class, where the swap_out variant's extra transposed buffers sit
    # closest to the Mosaic compile ceiling the VMEM formula does not
    # model. Complex cannot cross the host<->device boundary on this
    # backend, so the complex value is formed and split inside the jit.
    for n, p in ((64, 8), (320, 4)):
        xr = jnp.asarray(rng.standard_normal((p, n, n)), jnp.float32)
        xi = jnp.asarray(rng.standard_normal((p, n, n)), jnp.float32)
        m1 = dft.c2c_mats(n, dft.BACKWARD)
        m2 = dft.c2c_mats(n, dft.BACKWARD)

        def run(a, b, m1=m1, m2=m2):
            y = dft.cdft2_xy(a + 1j * b, m1, m2)
            return jnp.real(y), jnp.imag(y)

        gr, gi = jax.jit(run)(xr, xi)
        got = np.asarray(gr, np.float64) + 1j * np.asarray(gi, np.float64)
        want = np.fft.ifft2(np.asarray(xr, np.float64)
                            + 1j * np.asarray(xi, np.float64),
                            axes=(-2, -1)) * (n * n)
        assert _rel(got, want) < 1e-5
        hlo = jax.jit(run).lower(xr, xi).as_text()
        assert "tpu_custom_call" in hlo


def test_multi_transform_on_device():
    """multi_transform on the chip, both execution regimes: three
    clones of one plan (fused vmapped batch over the Pallas kernels —
    the path the CPU suite runs on XLA stages only) and two DISTINCT
    plans (per-transform async dispatch); each result must match the
    plan's own single execution."""
    from spfft_tpu import Transform
    from spfft_tpu.multi import (multi_transform_backward,
                                 multi_transform_forward)
    n = 48
    tr = spherical_cutoff_triplets(n)
    plan = make_local_plan(TransformType.C2C, n, n, n, tr,
                           precision="single")
    base = Transform(plan)
    clones = [base.clone() for _ in range(3)]
    vals = [_values(len(tr), s) for s in (21, 22, 23)]
    outs = multi_transform_backward(clones, vals)
    for o, v in zip(outs, vals):
        assert _rel(np.asarray(o), np.asarray(plan.backward(v))) < 1e-7
    fouts = multi_transform_forward(clones, [np.asarray(o) for o in outs])
    for f, o in zip(fouts, outs):
        want = np.asarray(plan.forward(np.asarray(o)))
        assert _rel(np.asarray(f), want) < 1e-7

    m = 40
    tr2 = spherical_cutoff_triplets(m)
    plan_b = make_local_plan(TransformType.C2C, m, m, m, tr2,
                             precision="single")
    pair = [Transform(plan), Transform(plan_b)]
    vals2 = [vals[0], _values(len(tr2), 24)]
    outs2 = multi_transform_backward(pair, vals2)
    assert _rel(np.asarray(outs2[0]),
                np.asarray(plan.backward(vals2[0]))) < 1e-7
    assert _rel(np.asarray(outs2[1]),
                np.asarray(plan_b.backward(vals2[1]))) < 1e-7


def test_prime_axis_direct_on_device():
    """617-point (prime > MATMUL_DFT_MAX) z-axis through the direct
    matmul fallback on real hardware — the round-5 coverage extension
    that keeps prime axes off the conv-lowered jnp.fft TPU path."""
    nx, ny, nz = 8, 8, 617
    rng = np.random.default_rng(31)
    tr = np.unique(np.stack([rng.integers(0, nx, 2000),
                             rng.integers(0, ny, 2000),
                             rng.integers(0, nz, 2000)], -1), axis=0)
    plan = make_local_plan(TransformType.C2C, nx, ny, nz, tr,
                           precision="single")
    assert plan._use_mdft
    vals = _values(len(tr), 32)
    space = np.asarray(plan.backward(vals))
    got = space[..., 0] + 1j * space[..., 1]
    oracle = _dense_c2c_oracle(tr, vals, (nx, ny, nz))
    assert _rel(got, oracle) < TOL
    out = np.asarray(plan.forward(space, Scaling.FULL))
    assert _rel(out[:, 0] + 1j * out[:, 1], vals) < TOL


def test_distributed_delegate_on_device():
    """A comm-size-1 distributed plan on the real chip: the S=1 mesh
    delegates to the local pipeline (reference grid_internal.cpp:182
    semantics), so the delegate glue — per-shard value slicing, plane
    accounting, the distributed API surface — runs over the fused
    kernels on hardware. CPU suites cover S>1 on the virtual mesh."""
    from spfft_tpu import make_distributed_plan

    n = 48
    tr = spherical_cutoff_triplets(n)
    plan = make_distributed_plan(TransformType.C2C, n, n, n, [tr], [n])
    vals = _values(len(tr), 41)
    space = np.asarray(plan.backward([vals])[0])
    got = space[..., 0] + 1j * space[..., 1]
    oracle = _dense_c2c_oracle(tr, vals, (n, n, n))
    assert _rel(got, oracle) < TOL
    out = np.asarray(plan.forward([space], Scaling.FULL)[0])
    assert _rel(out[:, 0] + 1j * out[:, 1], vals) < TOL


def test_serve_smoke_on_tpu():
    """The serving layer's deterministic pinning smoke ON THE CHIP: the
    adaptive exact-shape path (pinned batched executables, staged host
    buffers, zero pad rows) exercises real Mosaic/XLA:TPU executables
    here — the CPU tier-1 smoke covers the same logic but not the
    hardware dispatch. Also records a small on-chip serve trace so the
    TPU-regime serving numbers the ROADMAP calls for land in the CI log
    (window/max-batch retuning reads them from there)."""
    from spfft_tpu.serve.bench import main as serve_bench_main

    assert serve_bench_main(["--smoke"]) == 0
    # one small measured trace (printed JSON line lands in the CI log)
    assert serve_bench_main(["--dim", "24", "--requests", "64",
                             "--signatures", "2", "--threads", "4",
                             "--high-fraction", "0.25"]) == 0


def test_serve_fault_smoke_on_tpu():
    """The failure-semantics smoke ON THE CHIP: scripted faults drive
    bucket isolation, bounded retry, device quarantine/probation over
    the REAL device pool and the crash-proof dispatch supervisor
    against real Mosaic/XLA:TPU executables (the CPU tier-1 smoke
    covers the same logic but not hardware dispatch or a multi-chip
    pool). A short injected-fault trace is also measured so degraded
    TPU-regime serving numbers land in the CI log next to the clean
    trace from test_serve_smoke_on_tpu."""
    from spfft_tpu.serve.bench import main as serve_bench_main

    assert serve_bench_main(["--fault-smoke"]) == 0
    assert serve_bench_main(["--dim", "24", "--requests", "64",
                             "--signatures", "2", "--threads", "4",
                             "--fault-rate", "0.05"]) == 0


def test_obs_smoke_on_tpu(tmp_path):
    """Unified telemetry ON THE CHIP: the traced serving smoke must
    produce a Chrome trace covering all eight request stages with zero
    unclosed spans against real Mosaic/XLA:TPU executables (the CPU
    tier-1 smoke covers the same lifecycle logic but not hardware
    dispatch — on TPU the device_execute spans measure real async chip
    work and a multi-chip host exercises per-device tracks), plus
    Prometheus text that round-trips the exposition parser. The traced
    fault smoke then proves the zero-leak contract across bucket
    isolation / quarantine / crash recovery on the real device pool."""
    import json

    from spfft_tpu import obs
    from spfft_tpu.obs.__main__ import (REQUEST_STAGES,
                                        validate_trace_payload)
    from spfft_tpu.serve.bench import main as serve_bench_main

    trace_file = tmp_path / "tpu_trace.json"
    prom_file = tmp_path / "tpu_metrics.prom"
    try:
        assert serve_bench_main(["--smoke",
                                 "--trace-out", str(trace_file),
                                 "--prom-out", str(prom_file)]) == 0
        payload = json.loads(trace_file.read_text())
        assert validate_trace_payload(
            payload, require_names=REQUEST_STAGES) == []
        series = obs.parse_prometheus_text(prom_file.read_text())
        assert series[("spfft_trace_spans_open", ())] == 0
        assert serve_bench_main(
            ["--fault-smoke",
             "--trace-out", str(tmp_path / "tpu_fault_trace.json")]) == 0
    finally:
        obs.disable()
        obs.GLOBAL_TRACER.reset()


def test_overlap_exchange_on_tpu():
    """Compute/communication overlap ON REAL CHIPS (multi-chip hosts
    only — the chunked exchange needs a real mesh): overlap_chunks=K
    output must match the monolithic plan (rel <= 1e-6; the matmul-DFT
    z-stage may re-tile per chunk width, so bitwise equality is not
    guaranteed on TPU the way it is on the CPU suite), the compiled
    module must show the collective start/done split the chunk loop
    exists to enable (utils.hlo_inspect.collective_async_split), and a
    measured same-session A/B trace (monolithic vs K in {2,4}) lands in
    the CI log for BENCHMARKS.md's distributed-perf trajectory."""
    import json
    import time

    import jax

    from spfft_tpu import ExchangeType, make_distributed_plan
    from spfft_tpu.parallel import make_mesh
    from spfft_tpu.utils.hlo_inspect import (collective_async_split,
                                             count_collectives)
    from spfft_tpu.utils.workloads import (even_plane_split,
                                           round_robin_stick_partition)

    S = min(len(jax.devices()), 8)
    if S < 2:
        pytest.skip("overlap exchange A/B needs >= 2 TPU devices; "
                    f"this host exposes {len(jax.devices())}")
    n = 64
    tr = spherical_cutoff_triplets(n)
    parts = round_robin_stick_partition(tr, (n, n, n), S)
    planes = even_plane_split(n, S)
    mesh = make_mesh(S)
    rng = np.random.default_rng(0)
    vals = [(rng.uniform(-1, 1, len(p))
             + 1j * rng.uniform(-1, 1, len(p))).astype(np.complex64)
            for p in parts]
    rows = []
    ref_space = None
    for exchange in (ExchangeType.DEFAULT, ExchangeType.COMPACT_BUFFERED):
        for k in (1, 2, 4):
            plan = make_distributed_plan(
                TransformType.C2C, n, n, n, parts, planes, mesh=mesh,
                exchange=exchange, overlap_chunks=k)
            space = plan.backward(vals)
            got = np.asarray(space)
            if ref_space is None:
                ref_space = got
            else:  # bit-exact-or-1e-6 contract vs the monolithic result
                assert _rel(got[..., 0] + 1j * got[..., 1],
                            ref_space[..., 0] + 1j * ref_space[..., 1]) \
                    < TOL
            v = plan.shard_values(vals)
            lowered = plan._backward_jit.lower(v, *plan._device_tables)
            launches = sum(count_collectives(lowered.as_text()).values())
            split = collective_async_split(lowered.compile().as_text())
            if k > 1:
                assert launches >= k  # one collective per chunk
                # the latency-hiding scheduler split them: overlap is
                # structurally possible on this backend
                assert split["starts"] >= k
            # measured same-session A/B (pair wall-clock)
            out = plan.apply_pointwise(vals)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(10):
                out = plan.apply_pointwise(vals)
            jax.block_until_ready(out)
            rows.append({"exchange": exchange.value, "k": k,
                         "pair_ms": round(
                             (time.perf_counter() - t0) / 10 * 1e3, 3),
                         "collectives": launches,
                         "async_starts": split["starts"]})
    print("OVERLAP_AB " + json.dumps({"shards": S, "dim": n,
                                      "rows": rows}))


def test_symmetry_exchange_on_tpu():
    """Hermitian wire trimming ON REAL CHIPS, next to the overlap A/B:
    a folded full-sphere R2C plan must ship exactly the half-spectrum
    plan's bytes (table-derived accounting, conserved at every
    overlap_chunks=K), reproduce its backward grid on the real exchange
    (rel <= 1e-6 on chip; the CPU suite asserts bitwise), and land at
    <= 55% of the untrimmed C2C wire — the ISSUE r06 halving, measured
    where the bytes actually cross ICI links."""
    import json
    import jax

    from spfft_tpu import ExchangeType, make_distributed_plan
    from spfft_tpu.parallel import make_mesh
    from spfft_tpu.utils.workloads import (even_plane_split,
                                           round_robin_stick_partition)

    S = min(len(jax.devices()), 8)
    if S < 2:
        pytest.skip("symmetry exchange A/B needs >= 2 TPU devices; "
                    f"this host exposes {len(jax.devices())}")
    n = 64
    dims = (n, n, n)
    full = spherical_cutoff_triplets(n)
    x, y, z = full[:, 0], full[:, 1], full[:, 2]
    half = full[(x > 0) | ((x == 0) & ((y > 0)
                                       | ((y == 0) & (z >= 0))))]
    half_parts = round_robin_stick_partition(half, dims, S)
    # mirrors ride WITH their fold-target stick's shard
    full_parts = [np.concatenate([p, -p[p[:, 0] > 0]])
                  for p in half_parts]
    planes = even_plane_split(n, S)
    mesh = make_mesh(S)
    rng = np.random.default_rng(7)
    half_vals = [(rng.uniform(-1, 1, len(p))
                  + 1j * rng.uniform(-1, 1, len(p))).astype(np.complex64)
                 for p in half_parts]
    full_vals = [np.concatenate([v, np.conj(v[p[:, 0] > 0])])
                 for v, p in zip(half_vals, half_parts)]

    def build(ttype, parts, k):
        return make_distributed_plan(
            ttype, n, n, n, parts, planes, mesh=mesh,
            exchange=ExchangeType.COMPACT_BUFFERED, overlap_chunks=k)

    wires = []
    for k in (1, 2, 4):
        fp = build(TransformType.R2C, full_parts, k)
        hp = build(TransformType.R2C, half_parts, k)
        assert fp.exchange_wire_bytes() == hp.exchange_wire_bytes()
        wires.append(fp.exchange_wire_bytes())
        got = np.asarray(fp.backward(full_vals))
        ref = np.asarray(hp.backward(half_vals))
        assert _rel(got[..., 0] + 1j * got[..., 1],
                    ref[..., 0] + 1j * ref[..., 1]) < TOL
    assert wires[0] == wires[1] == wires[2]  # conserved across chunking
    # untrimmed baseline: the same sphere as C2C, storage coordinates
    # (the C2C centered bounds reject the hermitian-only -n/2 mirror)
    c2c = build(TransformType.C2C,
                [p % np.array(dims, np.int64) for p in full_parts], 1)
    ratio = wires[0] / c2c.exchange_wire_bytes()
    assert ratio <= 0.55, f"wire ratio {ratio:.3f} > 0.55"
    print("SYMMETRY_AB " + json.dumps({
        "shards": S, "dim": n, "r2c_wire_bytes": int(wires[0]),
        "c2c_wire_bytes": int(c2c.exchange_wire_bytes()),
        "ratio": round(ratio, 4)}))


def test_wire_precision_on_tpu():
    """The compressed exchange wire ON REAL CHIPS, next to the
    symmetry A/B: an int8-rung C2C plan must resolve its declared rung
    (budget honored by the build-time probe), ship <= 30% of the f32
    rung's wire bytes INCLUDING the per-stick scale sidecar (the ISSUE
    r06 acceptance, measured where the bytes actually cross ICI links),
    conserve that accounting at every overlap_chunks=K, and land its
    real-collective backward within the declared l2 budget of the
    rung-0 twin."""
    import json
    import jax

    from spfft_tpu import make_distributed_plan
    from spfft_tpu.parallel import make_mesh
    from spfft_tpu.utils.workloads import (even_plane_split,
                                           round_robin_stick_partition)

    S = min(len(jax.devices()), 8)
    if S < 2:
        pytest.skip("wire precision A/B needs >= 2 TPU devices; "
                    f"this host exposes {len(jax.devices())}")
    n = 64
    tr = spherical_cutoff_triplets(n)
    parts = round_robin_stick_partition(tr, (n, n, n), S)
    planes = even_plane_split(n, S)
    mesh = make_mesh(S)
    rng = np.random.default_rng(0x51F)
    # adversarial per-value dynamic range: the per-stick scales must
    # absorb it, not a global one
    mags = 10.0 ** rng.uniform(-4, 4, size=len(tr))
    off = 0
    vals = []
    for p in parts:
        m = mags[off:off + len(p)]
        off += len(p)
        vals.append(((rng.uniform(-1, 1, len(p))
                      + 1j * rng.uniform(-1, 1, len(p))) * m)
                    .astype(np.complex64))
    budget = 0.01

    def build(rung, k):
        return make_distributed_plan(
            TransformType.C2C, n, n, n, parts, planes, mesh=mesh,
            precision="single", overlap_chunks=k,
            wire_precision=rung, wire_error_budget=budget)

    wires, errs = [], []
    for k in (1, 2, 4):
        ip = build(3, k)
        fp = build(1, k)
        assert ip.wire_rung_name == "int8", ip.wire_declines
        assert ip.wire_probe_error <= budget
        wires.append(ip.exchange_wire_bytes())
        got = np.asarray(ip.backward(vals))
        ref = np.asarray(fp.backward(vals))
        err = _rel(got[..., 0] + 1j * got[..., 1],
                   ref[..., 0] + 1j * ref[..., 1])
        assert err <= budget, f"k={k}: int8 wire err {err:.2e} > budget"
        errs.append(err)
    assert wires[0] == wires[1] == wires[2]  # conserved across chunking
    f32_wire = build(1, 1).exchange_wire_bytes()
    ratio = wires[0] / f32_wire
    assert ratio <= 0.30, f"int8 wire ratio {ratio:.3f} > 0.30"
    print("WIRE_AB " + json.dumps({
        "shards": S, "dim": n, "int8_wire_bytes": int(wires[0]),
        "f32_wire_bytes": int(f32_wire), "ratio": round(ratio, 4),
        "budget": budget, "rel_l2": [round(float(e), 6) for e in errs]}))


def test_control_retune_on_tpu(tmp_path):
    """The round-11 closed loop on the real chip: the deterministic
    control smoke (scripted queue buildup -> recorded, bounds-clamped
    batch_window decision; SLO watchdog clean on the healthy trace)
    plus a measured replay with the live controller on — on-chip
    queue-wait vs device-execute ratios differ from the CPU lane, so
    this is where the controller's rules meet real dispatch latencies.
    Record the printed decisions when retuning defaults per the
    ROADMAP's on-chip backlog."""
    import json as _json

    from spfft_tpu.serve.bench import main as serve_bench_main

    assert serve_bench_main([
        "--smoke", "--control",
        "--trace-out", str(tmp_path / "control_tpu_trace.json"),
        "--prom-out", str(tmp_path / "control_tpu.prom")]) == 0
    prom = (tmp_path / "control_tpu.prom").read_text()
    assert "spfft_control_decisions_total" in prom
    assert "spfft_slo_burn_rate" in prom
    out = tmp_path / "control_tpu_replay.json"
    assert serve_bench_main([
        "--dim", "24", "--requests", "96", "--signatures", "3",
        "--threads", "4", "--control",
        "--slo", "p99_ms=60000,error_rate=0.1,max_quarantines=0",
        "-o", str(out)]) == 0
    payload = _json.loads(out.read_text())
    assert payload["failed_requests"] == 0
    assert payload["slo"]["violations"] == []
    from spfft_tpu.control import ServeConfig
    for knob, value in payload["control"]["knobs"].items():
        lo, hi = ServeConfig.bounds(knob)
        assert lo <= value <= hi


def test_fused_compression_dft_on_tpu(tmp_path, monkeypatch):
    """The fused compression+z-DFT kernels (ops/fused_kernel.py) on
    real Mosaic: both directions must pass the gate at 128^3 (dim_z a
    multiple of 128, under the axis cap), stay bit-exact vs the dense
    oracle AND the unfused two-kernel plan, and the --profile-dir
    device capture must no longer contain the dense stick-array
    intermediate the fusion exists to remove (the tier-1 twin asserts
    the same on lowered HLO; here it is checked against the real device
    profile). Record pair timings printed as FUSED_AB when retuning
    BENCHMARKS.md "Round-12" with chip numbers."""
    import glob
    import json
    import time

    import jax

    n = 128
    tr = spherical_cutoff_triplets(n)
    plan = make_local_plan(TransformType.C2C, n, n, n, tr,
                           precision="single", use_pallas=True)
    assert plan.pallas_active
    assert plan.fused_active, plan.fused_fallback_reasons
    assert plan.fused_fallback_reasons == {}
    space = _check_c2c(plan, tr, n, seed=11)  # dense-oracle bit-exact

    # A/B twin: same workload, fused path off -> the two-kernel plan
    monkeypatch.setenv("SPFFT_TPU_FUSED_COMPRESS", "0")
    plan_off = make_local_plan(TransformType.C2C, n, n, n, tr,
                               precision="single", use_pallas=True)
    assert not plan_off.fused_active
    vals = _values(len(tr), 11)
    np.testing.assert_allclose(space, np.asarray(plan_off.backward(vals)),
                               rtol=2e-6, atol=2e-6)

    def timed(p, v):
        out = p.backward(v)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(10):
            out = p.backward(v)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / 10

    ab = {"fused_s": timed(plan, vals), "unfused_s": timed(plan_off, vals)}
    print("FUSED_AB " + json.dumps(ab))

    # profile evidence: the unfused path materialises the dense gather
    # output (num_tiles, 8, 128) between the kernels; the fused capture
    # must not mention that buffer anywhere in the device profile
    dec = plan._pallas["dec"]
    n_tiles = (dec.num_super * dec.p_tiles
               if isinstance(dec, gk.WideGatherTables) else dec.num_tiles)
    token = ("%dx8x128" % n_tiles).encode()

    def capture(p, sub):
        d = tmp_path / sub
        jax.profiler.start_trace(str(d))
        jax.block_until_ready(p.backward(vals))
        jax.profiler.stop_trace()
        blob = b""
        for f in glob.glob(str(d / "**" / "*"), recursive=True):
            try:
                with open(f, "rb") as fh:
                    blob += fh.read()
            except (IsADirectoryError, OSError):
                pass
        return blob

    unfused_blob = capture(plan_off, "unfused")
    fused_blob = capture(plan, "fused")
    assert len(fused_blob) > 0
    if token in unfused_blob:  # the capture format names buffer shapes
        assert token not in fused_blob, \
            "dense stick intermediate still present in the fused profile"
    else:
        # profile format carries no shape strings on this runtime: the
        # HLO-level assertion is the backstop (tier-1 twin + here)
        text = jax.jit(
            lambda v: plan._backward_impl(v, plan._tables_hot)).lower(
                plan._coerce_values(vals)).as_text()
        assert ("%dx8x128xf32" % n_tiles) not in text


def test_plan_store_on_tpu(tmp_path):
    """The round-13 persistent plan-artifact store on the real chip:
    a warm load must (1) resolve with ZERO builds and restore the
    Pallas/fused kernel tables ACTIVE (the table cover build — seconds
    at this size — is the biggest cold-start line item the artifact
    exists to persist), (2) stay bit-exact vs the cold-built plan, and
    (3) first-execute FASTER through the jax.export AOT deserialize
    than a fresh trace+compile of the identical plan. Record the
    printed STORE_AB line into BENCHMARKS.md "Round-13" chip rows."""
    import time

    from spfft_tpu.serve.registry import PlanRegistry
    from spfft_tpu.serve.store import PlanArtifactStore

    n = 128
    tr = spherical_cutoff_triplets(n)
    store = PlanArtifactStore(str(tmp_path / "store"))
    reg = PlanRegistry(store=store)
    t0 = time.perf_counter()
    sig, plan = reg.get_or_build(TransformType.C2C, n, n, n, tr)
    plan._finalize()            # cold pays the whole table build
    cold_s = time.perf_counter() - t0
    store.drain()
    vals = _values(len(tr), 13)
    want = np.asarray(plan.backward(vals))

    # warm boot: fresh registry over the populated store
    reg2 = PlanRegistry(store=PlanArtifactStore(store.root))
    t0 = time.perf_counter()
    sig2, plan2 = reg2.get_or_build(TransformType.C2C, n, n, n, tr)
    load_s = time.perf_counter() - t0
    assert sig2 == sig
    assert reg2.stats()["builds"] == 0
    assert reg2.stats()["store_hits"] == 1
    assert plan2._build_thread is None
    assert plan2.pallas_active == plan.pallas_active
    assert plan2.fused_active == plan.fused_active
    assert plan2._aot is not None and "backward" in plan2._aot
    t0 = time.perf_counter()
    got = np.asarray(plan2.backward(vals))
    aot_first_s = time.perf_counter() - t0
    assert np.array_equal(got, want)    # bit-exact vs the cold build

    # fresh-compile twin: the SAME artifact with the AOT executables
    # stripped — identical restore cost, only trace+compile differs
    loaded = PlanArtifactStore(store.root).load_signature(sig)
    assert loaded is not None
    _, plan3 = loaded
    plan3._aot = None
    t0 = time.perf_counter()
    out3 = np.asarray(plan3.backward(vals))
    fresh_first_s = time.perf_counter() - t0
    assert np.array_equal(out3, want)
    print(f"STORE_AB n={n} cold_resolve={cold_s * 1e3:.1f}ms "
          f"warm_load={load_s * 1e3:.1f}ms "
          f"aot_first_execute={aot_first_s * 1e3:.1f}ms "
          f"fresh_first_execute={fresh_first_s * 1e3:.1f}ms")
    assert load_s < cold_s, "warm load failed to beat the cold build"
    assert aot_first_s < fresh_first_s, \
        "AOT deserialize failed to beat the fresh trace+compile"


def test_fused_overlap_on_tpu(monkeypatch):
    """Fusion x overlap ON REAL CHIPS (multi-chip hosts only): a plan
    with overlap_chunks=K>1 and use_pallas=True must run BOTH fused
    distributed twins (chunk-sliceable decompress+z-DFT backward,
    post-exchange z-DFT+compress forward) while keeping the per-chunk
    collective structure — K collectives split into async start/done
    pairs by the latency-hiding scheduler — and match the monolithic
    UNFUSED oracle (rel <= 1e-6; the Mosaic matmul accumulation order
    differs from the XLA z-stage, so bitwise equality is the CPU
    interpret suite's contract, tests/test_fused_dist.py). The
    measured same-session A/B (unfused-monolithic vs fused xK) prints
    as FUSED_OVERLAP_AB for BENCHMARKS.md's chip trajectory."""
    import json
    import time

    import jax

    from spfft_tpu import ExchangeType, make_distributed_plan
    from spfft_tpu.parallel import make_mesh
    from spfft_tpu.utils.hlo_inspect import (collective_async_split,
                                             count_collectives)
    from spfft_tpu.utils.workloads import (even_plane_split,
                                           round_robin_stick_partition,
                                           sort_triplets_stick_major)

    S = min(len(jax.devices()), 8)
    if S < 2:
        pytest.skip("fused overlap A/B needs >= 2 TPU devices; "
                    f"this host exposes {len(jax.devices())}")
    # the random spherical workload's window-overlap recompute can trip
    # the default forward cost gate at toy densities — widen it with
    # the declared knob (control/config.py fused_recompute_limit)
    monkeypatch.setenv("SPFFT_TPU_FUSED_RECOMPUTE_LIMIT", "16")
    nx = ny = 64
    nz = 128  # dim_z % 128 == 0: the fused eligibility floor
    tr = spherical_cutoff_triplets(nx, radius=nx // 2 - 1)
    tr = np.stack([tr[:, 0], tr[:, 1], tr[:, 2] * 2], axis=1)
    dims = (nx, ny, nz)
    parts = [sort_triplets_stick_major(p, dims)
             for p in round_robin_stick_partition(tr, dims, S)]
    planes = even_plane_split(nz, S)
    mesh = make_mesh(S)
    rng = np.random.default_rng(5)
    vals = [(rng.uniform(-1, 1, len(p))
             + 1j * rng.uniform(-1, 1, len(p))).astype(np.complex64)
            for p in parts]

    def build(use_pallas, k):
        return make_distributed_plan(
            TransformType.C2C, nx, ny, nz, parts, planes, mesh=mesh,
            exchange=ExchangeType.BUFFERED, overlap_chunks=k,
            precision="single", use_pallas=use_pallas)

    ref = build(False, 1)                 # monolithic unfused oracle
    assert not ref.fused_dist_active
    ref_space = np.asarray(ref.backward(vals))
    ref_fwd = np.asarray(ref.forward(ref.backward(vals)))

    def timed_pair(p):
        out = p.apply_pointwise(vals)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(10):
            out = p.apply_pointwise(vals)
        jax.block_until_ready(out)
        return round((time.perf_counter() - t0) / 10 * 1e3, 3)

    rows = [{"fused": False, "k": 1, "pair_ms": timed_pair(ref)}]
    for k in (1, 2, 4):
        plan = build(True, k)
        # the composition this round exists for: fusion AND overlap,
        # both directions, no decline
        assert plan.fused_dist_active, (
            plan.fused_dist_fallback_reason,
            plan.fused_dist_fwd_fallback_reason)
        assert plan.fused_dist_fallback_reason is None
        assert plan.fused_dist_fwd_fallback_reason is None
        # fusion and chunking move no extra bytes over the wire
        assert plan.exchange_wire_bytes() == ref.exchange_wire_bytes()
        space = plan.backward(vals)
        got = np.asarray(space)
        assert _rel(got[..., 0] + 1j * got[..., 1],
                    ref_space[..., 0] + 1j * ref_space[..., 1]) < TOL
        fwd = np.asarray(plan.forward(space))
        assert _rel(fwd[..., 0] + 1j * fwd[..., 1],
                    ref_fwd[..., 0] + 1j * ref_fwd[..., 1]) < TOL
        v = plan.shard_values(vals)
        lowered = plan._backward_jit.lower(v, *plan._device_tables)
        launches = sum(count_collectives(lowered.as_text()).values())
        split = collective_async_split(lowered.compile().as_text())
        if k > 1:
            assert launches >= k  # one collective per fused chunk
            # start/done evidence WITH fusion active: the scheduler can
            # still hide chunk i-1's exchange behind chunk i's launch
            assert split["starts"] >= k
        rows.append({"fused": True, "k": k, "pair_ms": timed_pair(plan),
                     "collectives": launches,
                     "async_starts": split["starts"]})
    print("FUSED_OVERLAP_AB " + json.dumps({"shards": S, "dims": dims,
                                            "rows": rows}))

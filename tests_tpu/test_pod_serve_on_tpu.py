"""On-TPU pod-serving twin (make ci-tpu): the 2-host emulated pod over
REAL device execution.

The CPU pod lane (tests/test_cluster.py + make cluster-smoke) proves
routing, reconciliation, federated telemetry and failure semantics
over the virtual 8-device platform; this lane re-proves the two
behaviours where the chip is load-bearing:

  * the pod-wide SPMD lane executing a real shard_map distributed plan
    across the local chip mesh, bit-exact vs direct execution;
  * power-of-two-choices routing fed by REAL device-execute latencies
    (the ``device_execute_p50`` half of the load score is genuine chip
    timing, not interpret-mode noise).
"""

import numpy as np
import pytest

import jax

from spfft_tpu.benchmark import cutoff_stick_triplets
from spfft_tpu.parallel import make_distributed_plan, make_mesh
from spfft_tpu.serve.cluster import PodFrontend, _run_smoke
from spfft_tpu.serve.executor import ServeExecutor
from spfft_tpu.serve.registry import PlanRegistry, signature_for
from spfft_tpu.types import TransformType
from spfft_tpu.utils.workloads import (even_plane_split,
                                       round_robin_stick_partition)

N = 32
SHARDS = 2


def _shards_available():
    return len(jax.devices()) >= SHARDS


@pytest.mark.skipif(not _shards_available(),
                    reason=f"needs >= {SHARDS} devices")
def test_pod_smoke_on_tpu():
    """The full cluster smoke body on the real chip: mixed traffic
    bit-exact, trace nesting across the host boundary, federated
    /metrics, lane-death failover and the routing-simulation gates."""
    assert _run_smoke(seed=0) == 0


@pytest.mark.skipif(not _shards_available(),
                    reason=f"needs >= {SHARDS} devices")
def test_pod_spmd_lane_distributed_bit_exact_on_tpu():
    """A realistic-size distributed plan through the frontend's SPMD
    lane on real devices, bit-exact vs calling the plan directly."""
    dims = (N, N, N)
    trip = cutoff_stick_triplets(N, N, N, 0.7, hermitian=False)
    parts = round_robin_stick_partition(trip, dims, SHARDS)
    planes = even_plane_split(dims[2], SHARDS)
    dplan = make_distributed_plan(TransformType.C2C, *dims, parts,
                                  planes, mesh=make_mesh(SHARDS),
                                  precision="single")
    dsig = signature_for(TransformType.C2C, *dims, trip,
                         precision="single", device_count=SHARDS)
    rng = np.random.default_rng(0)
    dvalues = [
        (rng.standard_normal(sp.num_values)
         + 1j * rng.standard_normal(sp.num_values)).astype(np.complex64)
        for sp in dplan.dist_plan.shard_plans]

    lanes = []
    for host in ("h0", "h1"):
        reg = PlanRegistry(store=False)
        reg.put(dsig, dplan)
        lanes.append((host, ServeExecutor(reg)))
    pod = PodFrontend(lanes, seed=0)
    try:
        got = np.asarray(pod.submit(dsig, dvalues).result(timeout=300))
        want = np.asarray(dplan.backward(dvalues))
        assert np.array_equal(got, want)
    finally:
        pod.close()

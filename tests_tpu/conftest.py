"""On-TPU CI lane configuration (make ci-tpu).

Unlike tests/ (which pins JAX_PLATFORMS=cpu and a virtual 8-device mesh
so distributed logic runs anywhere), this lane runs on the REAL chip:
Mosaic codegen, T(8,128) layout behavior, pair-IO boundaries and the
wide-kernel DMA path have a documented history of silent corruption
(the round-2 rank-3 irfft bug, the round-4 wide-kernel compile crash)
that CPU-pinned tests structurally cannot see — round-4 verdict item 3.

Recorded green log: docs/ci_tpu_r05.log.
"""

import jax
import pytest


def pytest_runtest_setup(item):
    if jax.default_backend() != "tpu":
        pytest.skip("ci-tpu lane requires the real TPU backend "
                    "(run tests/ for the CPU suite)")


@pytest.fixture(scope="session")
def tpu_device():
    return jax.devices()[0]

"""On-TPU chaos twin (make ci-tpu): the seeded multi-seam fault storms
and the fused-demotion ladder against REAL Mosaic kernels and real
device dispatch.

The CPU chaos lane (tests/test_serve_bench_cli.py::
test_serve_bench_chaos_harness + make chaos-smoke) proves the recovery
ladders over interpret-mode kernels; this lane re-proves the two
behaviours where the hardware itself is load-bearing:

  * a kernel.launch fault demoting a REAL fused Mosaic kernel to the
    unfused composition, bit-exact, with the re-probe running actual
    codegen again;
  * a full storm sweep where injected faults race genuine device
    dispatch/transfer latencies instead of interpret-mode timing.
"""

import numpy as np

from spfft_tpu import Scaling, TransformType, faults, make_local_plan
from spfft_tpu.serve.bench import main

DIM_Z = 128


def _gappy_triplets(nx=8, ny=6, nz=DIM_Z, z_step=2):
    return [(x, y, z) for x in range(nx) for y in range(ny)
            if (x + y) % 3 != 0 for z in range(0, nz, z_step)]


def _values(n, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(n)
            + 1j * rng.standard_normal(n)).astype(np.complex64)


def test_chaos_harness_on_tpu(capsys):
    """The full seeded chaos run on the real chip: same invariants (no
    hangs, typed failures only, bit-exact healthy requests, clean
    store, zero open spans), real kernels and device queues underneath.
    A different seed from the CPU lane's, on purpose."""
    try:
        rc = main(["--chaos", "31"])
    finally:
        faults.disarm()
    assert rc == 0


def test_fused_demotion_on_real_mosaic():
    """Runtime demotion with a REAL fused Mosaic kernel: the injected
    launch fault demotes dec, the unfused retry is bit-exact against
    the pre-fault fused output, and the re-probe (a genuine second
    Mosaic dispatch) readmits."""
    tr = _gappy_triplets()
    plan = make_local_plan(TransformType.C2C, 8, 6, DIM_Z,
                           np.asarray(tr, np.int32),
                           precision="single", use_pallas=True)
    vals = _values(plan.index_plan.num_values)
    want = np.asarray(plan.backward(vals))  # fused, healthy
    assert plan.fused_demotions() == {}
    try:
        faults.arm(faults.FaultPlan(script="kernel.launch@1"))
        got = np.asarray(plan.backward(vals))
    finally:
        faults.disarm()
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    assert set(plan.fused_demotions()) == {"dec"}

    for _ in range(plan.FUSED_REPROBE_AFTER):
        plan.backward(vals)
    assert plan.fused_demotions()["dec"]["probing"]
    got = np.asarray(plan.backward(vals))  # the probe: real codegen
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    assert plan.fused_demotions() == {}

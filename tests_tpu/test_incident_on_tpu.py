"""On-TPU flight-recorder twin (make ci-tpu): the incident loop over
REAL device execution.

tests/test_recorder.py proves the full tail-retention + pod-bundle
loop on the CPU-pinned virtual mesh; this lane re-proves the two
behaviours where the chip is load-bearing:

  * tail retention triggered by REAL device-execute spans — the
    retained errored trace's Chrome events carry genuine chip stage
    timings, not interpret-mode noise, under head sampling 0.0;
  * a pod incident bundle captured while a real-chip pod is serving
    validates end-to-end and is written atomically (no torn file)
    even with device work in flight.
"""

import json
import os

import numpy as np
import pytest

from spfft_tpu import obs
from spfft_tpu.benchmark import cutoff_stick_triplets
from spfft_tpu.errors import GenericError
from spfft_tpu.obs import recorder
from spfft_tpu.serve.cluster import PodFrontend
from spfft_tpu.serve.executor import ServeExecutor
from spfft_tpu.serve.registry import PlanRegistry
from spfft_tpu.types import TransformType

N = 32


@pytest.fixture(autouse=True)
def recorder_isolation():
    obs.disable_recorder()
    recorder.reset_recorder()
    yield
    obs.disable_recorder()
    recorder.reset_recorder()
    obs.GLOBAL_TRACER.set_sample_rate(1.0)
    obs.disable()


def test_incident_loop_on_tpu(tmp_path):
    dims = (N, N, N)
    trip = cutoff_stick_triplets(N, N, N, 0.7, hermitian=False)
    reg = PlanRegistry(store=False)
    sig, plan = reg.get_or_build(TransformType.C2C, *dims, trip,
                                 precision="single")
    obs.enable()
    obs.GLOBAL_TRACER.reset()
    obs.GLOBAL_TRACER.set_sample_rate(0.0)  # head sampling OFF
    obs.enable_recorder(incident_dir=str(tmp_path),
                        min_interval_s=0.0)
    lanes = []
    for host in ("h0", "h1"):
        r = PlanRegistry(store=False)
        r.put(sig, plan)
        lanes.append((host, ServeExecutor(r)))
    pod = PodFrontend(lanes, seed=0)
    rng = np.random.default_rng(0)
    try:
        for _ in range(4):
            v = (rng.standard_normal(len(trip))
                 + 1j * rng.standard_normal(len(trip))) \
                .astype(np.complex64)
            got = np.asarray(pod.submit_backward(sig, v)
                             .result(timeout=300))
            assert np.array_equal(got, np.asarray(plan.backward(v)))
        # typed failure -> tail-retained trace with REAL chip spans
        with pytest.raises(GenericError):
            pod.submit_backward(sig,
                                np.zeros(3)).result(timeout=300)
        err = [t for t in obs.retained_traces()
               if t["reason"] == "error"]
        assert err, "errored trace not tail-retained on the chip"
        # pod bundle captured mid-serve: validates, atomically written
        path = pod.capture_incident("tpu-ci")
        assert path is not None
        with open(path) as f:
            bundle = json.load(f)
        assert obs.validate_bundle(bundle) == []
        assert bundle["kind"] == "pod"
        assert set(bundle["hosts"]) == {"h0", "h1"}
        assert not any(n.endswith(".tmp")
                       for n in os.listdir(tmp_path))
        assert obs.GLOBAL_TRACER.open_count() == 0
        # still serving after capture
        v = (rng.standard_normal(len(trip))
             + 1j * rng.standard_normal(len(trip))) \
            .astype(np.complex64)
        got = np.asarray(pod.submit_backward(sig, v)
                         .result(timeout=300))
        assert np.array_equal(got, np.asarray(plan.backward(v)))
    finally:
        pod.close()

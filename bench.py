#!/usr/bin/env python
"""North-star benchmark: 256^3 spherical-cutoff C2C forward+backward pair.

Driver metric (BASELINE.json): wall-clock of a backward+forward pair on a
256^3 grid with a spherical-cutoff sparse frequency set, plus L2 error vs a
dense FFT oracle. Mirrors the reference benchmark workload
(reference: tests/programs/benchmark.cpp:176-205 builds a dense-within-cutoff
stick set; :84-96 times repeated backward+forward pairs).

Baseline: the reference publishes no numbers (BASELINE.md) and this container
has no FFTW/CUDA to build its benchmark, so the baseline is *generated* here:
the same sparse algorithm (stick z-FFTs -> scatter -> plane FFTs) run on CPU
via scipy's pocketfft with all available cores (workers=-1) — the moral
equivalent of the reference host path on this machine. ``vs_baseline`` is
baseline_seconds / tpu_seconds (>1 means faster than baseline).

Prints exactly one JSON line at the end:
  {"metric": ..., "value": ..., "unit": "s", "vs_baseline": ...}

Session handling: round 5 resolved the round-4 "bimodal device" as
bimodal SYNC-READBACK cost (~88 vs ~128 ms per hard sync, constant per
group regardless of group size — scripts/probe_r5_mode.py), not bimodal
compute. The old min-of-single-diffs statistic fabricated 8.6-9.5 ms
readings whenever the two group sizes caught mismatched sync modes; the
estimator now differences MEDIANS of several samples per group size
(utils/benchtime.py), which is immune to the mismatch. The measurement
still runs in SPFFT_BENCH_SESSIONS (default 4) fresh backend sessions
(compile/backend variance) and reports the best session — disclosed in
the metric string together with every session's value. Optimisation
decisions still require interleaved multi-process A/B
(scripts/ab_interleaved.py): two round-4 same-session "wins" reverted
under interleaving.

Env knobs: SPFFT_BENCH_DIM (default 256), SPFFT_BENCH_REPS (default 30),
SPFFT_BENCH_SESSIONS (default 4, set 1 to disable re-rolling),
SPFFT_BENCH_SKIP_BASELINE=1 to skip the CPU baseline (vs_baseline = 0).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


#: A backend session is flagged as an outlier when its value exceeds
#: this factor times the MEDIAN of all sessions. Provenance: the r05
#: best-of-4 line carried a 274.74 ms session next to 10.6-11 ms ones —
#: a backend/tunnel hiccup, not a compute mode. The best-of statistic
#: was already immune (min is never a high outlier), but the disclosed
#: per-session list distorted cross-round trajectory comparisons, so
#: hiccup sessions are split out and labelled instead of silently mixed
#: into the healthy list.
SESSION_OUTLIER_FACTOR = 2.5


def split_outlier_sessions(values):
    """Partition session values into (kept, outliers) around
    ``SESSION_OUTLIER_FACTOR x median``. The median includes every
    session, so one hiccup among >= 3 healthy sessions cannot shift the
    threshold onto healthy values; with k < 3 sessions nothing is ever
    flagged (too few samples to call anything an outlier)."""
    import statistics
    if len(values) < 3:
        return list(values), []
    cut = SESSION_OUTLIER_FACTOR * statistics.median(values)
    kept = [v for v in values if v <= cut]
    return kept, [v for v in values if v > cut]


def symmetry_rows() -> dict:
    """The hermitian-symmetry sub-rows, computed in a forced-CPU
    subprocess (fresh interpreter: the accounting is backend-independent
    and must not claim this process's backend):

    * ``wire_bytes_r2c`` — table-derived aggregate exchange wire bytes
      of the trimmed R2C distributed plan on the flagship spherical
      workload (deterministic accounting, no execution);
    * ``fused_r2c`` — how many of the two r2c fused seams (local
      backward kernel + distributed pre-exchange twin) are ACTIVE on
      the interpret lane (deterministic; 2 = the r2c decline stays
      lifted);
    * ``pod_routing`` — the round-18 pod frontend's skewed-trace
      imbalance reduction, rr completed-work skew over p2c skew
      (seeded discrete-event replay of the real ``load_score``;
      deterministic, so a drop means the routing policy regressed);
    * ``pod_wire`` / ``pod_wire_pooled`` — TCP-vs-loopback rpc_submit
      overhead through an in-process localhost HostAgent, on the
      connect-per-RPC wire and the kept-alive pooled wire;
    * ``spmd_coalesce`` — distributed requests per collective round
      for a concurrent same-signature burst through the pod SPMD
      coalescer (deterministic scheduler accounting);
    * ``recorder_overhead`` — per-request hot-path cost of the ARMED
      flight recorder (journal + tail retention) minus the disarmed
      path, from the deterministic ``obs.recorder.overhead_probe``
      micro A/B (the disarmed path itself is budgeted at <= 1% of a
      request in tests/test_recorder.py).

    Returns {} (with a stderr note) if the probe subprocess fails —
    the primary measurement must not die on an accounting row.
    """
    env = dict(os.environ, SPFFT_BENCH_SYMMETRY_INNER="1",
               JAX_PLATFORMS="cpu",
               SPFFT_TPU_FORCE_MATMUL_DFT="1",
               SPFFT_TPU_FUSED_INTERPRET="1")
    proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                          capture_output=True, text=True, env=env)
    line = next((ln for ln in reversed(proc.stdout.splitlines())
                 if ln.startswith("{")), None)
    if proc.returncode != 0 or line is None:
        sys.stderr.write("symmetry sub-row probe failed (rows omitted):\n"
                         + proc.stdout[-1000:] + proc.stderr[-1000:])
        return {}
    return json.loads(line)


def symmetry_inner() -> None:
    """SPFFT_BENCH_SYMMETRY_INNER=1: compute the symmetry sub-rows on a
    virtual-CPU backend and print them as one JSON line."""
    from spfft_tpu.utils.platform import force_virtual_cpu_devices
    force_virtual_cpu_devices(2)
    from spfft_tpu import TransformType, make_local_plan
    from spfft_tpu.parallel import make_distributed_plan, make_mesh
    from spfft_tpu.parallel.dist import build_distributed_plan
    from spfft_tpu.parallel.exchange import build_ragged_schedule
    from spfft_tpu.utils.workloads import (
        even_plane_split, round_robin_stick_partition,
        sort_triplets_stick_major, spherical_cutoff_triplets)

    # --- wire_bytes_r2c: host-side accounting only, no device work ---
    n = int(os.environ.get("SPFFT_BENCH_DIM", "256"))
    shards = 8
    full = spherical_cutoff_triplets(n)
    x, y, z = full[:, 0], full[:, 1], full[:, 2]
    # the non-redundant hermitian half: x > 0 plus the x = 0 plane's
    # canonical half-spectrum (docs/distributed.md "Hermitian symmetry")
    half = full[(x > 0) | ((x == 0) & ((y > 0) | ((y == 0) & (z >= 0))))]
    planes = even_plane_split(n, shards)
    dims = (n, n, n)
    elem = 8  # complex64 wire
    r2c_wire = build_ragged_schedule(build_distributed_plan(
        TransformType.R2C, n, n, n,
        round_robin_stick_partition(half, dims, shards),
        planes)).wire_elements() * elem
    c2c_dp = build_distributed_plan(
        TransformType.C2C, n, n, n,
        round_robin_stick_partition(full, dims, shards), planes)
    c2c_wire = build_ragged_schedule(c2c_dp).wire_elements() * elem

    # --- wire_bytes_int8: the compressed-wire ladder's bottom rung ---
    # Padded block layout (the only mechanism that carries the int8
    # rung: the scale sidecar rides each slot's row through the SAME
    # collective), same 256^3 spherical set and shard count. Backward
    # convention, scales included — one f32 absmax scale per
    # (slot, stick row). Compared against the f32 wire on the SAME
    # layout, so the ratio isolates the rung, not the layout.
    ms, mp = c2c_dp.max_sticks, c2c_dp.max_planes
    links = shards * (shards - 1)
    int8_wire = links * (ms * mp * 2 + ms * 4)
    f32_wire = links * ms * mp * 8

    # --- wire_error_int8: measured end-to-end rel-l2 of the rung ---
    # A real 2-shard int8-wire plan on the virtual-CPU mesh vs its
    # rung-0 twin: seeded spectrum with adversarial 10^+-4 per-value
    # dynamic range through the actual quantized collective.
    wn = 32
    wfull = spherical_cutoff_triplets(wn)
    wparts = round_robin_stick_partition(wfull, (wn, wn, wn), 2)
    wplanes = even_plane_split(wn, 2)
    w_ref = make_distributed_plan(
        TransformType.C2C, wn, wn, wn, wparts, wplanes,
        mesh=make_mesh(2), precision="single", wire_precision=0)
    w_int8 = make_distributed_plan(
        TransformType.C2C, wn, wn, wn, wparts, wplanes,
        mesh=make_mesh(2), precision="single", wire_precision=3)
    wrng = np.random.default_rng(0xA11)
    wv = [wrng.standard_normal(p.num_values)
          * 10.0 ** wrng.uniform(-4, 4, p.num_values)
          + 1j * wrng.standard_normal(p.num_values)
          for p in w_ref.dist_plan.shard_plans]
    ref_out = np.asarray(w_ref.backward(wv), np.float64)
    int8_out = np.asarray(w_int8.backward(wv), np.float64)
    wire_err = float(np.linalg.norm(int8_out - ref_out)
                     / np.linalg.norm(ref_out))

    # --- fused_r2c: the two r2c fused seams on the interpret lane ---
    fd = (8, 6, 128)  # dim_z % 128 == 0: fused eligibility floor
    xs, ys, zs = (np.arange(0, fd[0] // 2),
                  np.arange(-(fd[1] // 2 - 1), fd[1] // 2 + 1),
                  np.arange(-(fd[2] // 2 - 1), fd[2] // 2 + 1))
    X, Y, Z = np.meshgrid(xs, ys, zs, indexing="ij")
    t = np.stack([X.ravel(), Y.ravel(), Z.ravel()], axis=1)
    t = t[(t[:, 0] > 0) | ((t[:, 1] > 0) | ((t[:, 1] == 0)
                                            & (t[:, 2] >= 0)))]
    t = sort_triplets_stick_major(t, fd)
    local = make_local_plan(TransformType.R2C, *fd, t,
                            precision="single", use_pallas=True)
    fparts = [sort_triplets_stick_major(p, fd)
              for p in round_robin_stick_partition(t, fd, 2)]
    fplanes = even_plane_split(fd[2], 2)
    dist = make_distributed_plan(
        TransformType.R2C, *fd, fparts, fplanes, mesh=make_mesh(2),
        precision="single", use_pallas=True)
    # backward-twin activity, the seam this row has always counted
    # (the forward twin reports through the fused_dist row below)
    active = int(bool(local.fused_active)) + int(bool(
        dist.fused_dist_bwd_active))

    # --- fused_dist: both fused directions composed with overlap ---
    dist_ov = make_distributed_plan(
        TransformType.R2C, *fd, fparts, fplanes, mesh=make_mesh(2),
        precision="single", use_pallas=True, overlap_chunks=2)
    dist_active = (int(bool(dist_ov.fused_dist_bwd_active))
                   + int(bool(dist_ov.fused_dist_fwd_active)))

    # --- pod_routing: p2c-vs-rr skew on the recorded skewed trace ---
    from spfft_tpu.serve.cluster import simulate_routing
    rr = simulate_routing("rr")
    p2c = simulate_routing("p2c")

    # --- pod_wire: what the real TCP wire costs over loopback ---
    from spfft_tpu.net.transport import wire_overhead_probe
    wire = wire_overhead_probe(repeats=48)

    # --- spmd_coalesce: requests per collective round in a burst ---
    # 12 concurrent same-signature distributed requests against the
    # pod SPMD coalescer (default spmd_max_batch 8): the window drains
    # them in ceil(12/8) = 2 rounds, so a healthy scheduler scores
    # 6.0 req/round. Duck-typed plan — the row measures the SCHEDULER
    # (bit-exactness of the batched math is tier-1's job), so it is
    # deterministic on any backend.
    from spfft_tpu.control.config import global_config
    from spfft_tpu.serve.cluster import SPMDCoalescer
    from spfft_tpu.types import Scaling

    class _BurstPlan:
        def coalesce_backward(self, values_list):
            return list(values_list)

    cfg = global_config()
    old_knobs = (cfg.spmd_batch_window, cfg.max_queue)
    cfg.set("spmd_batch_window", 0.25, source="bench",
            reason="spmd_coalesce row burst window")
    cfg.set("max_queue", 64, source="bench",
            reason="spmd_coalesce row burst depth")
    lane = SPMDCoalescer(max_workers=1)
    burst = 12
    try:
        futs = [lane.submit("bench-spmd", _BurstPlan(), i, "backward",
                            Scaling.NONE, None) for i in range(burst)]
        for f in futs:
            f.result(timeout=60)
        spmd_sig = lane.signals()
    finally:
        cfg.set("spmd_batch_window", old_knobs[0], source="bench",
                reason="restore after spmd_coalesce row")
        cfg.set("max_queue", old_knobs[1], source="bench",
                reason="restore after spmd_coalesce row")
        lane.close()
    per_round = burst / max(spmd_sig["spmd_launches"], 1)

    # --- recorder_overhead: armed-vs-disarmed hot path micro A/B ---
    from spfft_tpu.obs.recorder import overhead_probe
    rec = overhead_probe()

    print(json.dumps({
        "wire_bytes_r2c": {
            "metric": f"{n}^3 spherical-cutoff R2C distributed exchange "
                      f"aggregate wire bytes ({shards} shards, compact "
                      f"schedule, table-derived accounting): hermitian-"
                      f"trimmed non-redundant stick set "
                      f"({len(half)} of {len(full)} values; untrimmed "
                      f"C2C wire {c2c_wire} B, ratio "
                      f"{r2c_wire / c2c_wire:.3f})",
            "value": int(r2c_wire),
            "unit": "bytes",
        },
        "fused_r2c": {
            "metric": "r2c fused seams ACTIVE on the interpret lane "
                      "(local decompress+z-DFT backward kernel + "
                      "distributed pre-exchange twin; 2 = the "
                      "hermitian_completion decline stays lifted, "
                      f"fallbacks: local={local.fused_fallback_reasons} "
                      f"dist={dist.fused_dist_fallback_reason})",
            "value": active,
            "unit": "seams",
        },
        "fused_dist": {
            "metric": "distributed fused directions ACTIVE under the "
                      "K=2 overlap pipeline (chunk-sliceable "
                      "decompress+z-DFT backward + post-exchange "
                      "z-DFT+compress forward twin; 2 = fusion and "
                      "overlap compose in both directions, reasons: "
                      f"bwd={dist_ov.fused_dist_fallback_reason} "
                      f"fwd={dist_ov.fused_dist_fwd_fallback_reason})",
            "value": dist_active,
            "unit": "directions",
        },
        "pod_routing": {
            "metric": "pod frontend skewed-trace imbalance reduction: "
                      "round-robin completed-work skew over p2c skew, "
                      "seeded discrete-event replay of the live "
                      "load_score (rr "
                      f"{rr['ratio']:.2f}x vs p2c {p2c['ratio']:.2f}x; "
                      "python -m spfft_tpu.serve.cluster --simulate)",
            "value": round(rr["ratio"] / p2c["ratio"], 3),
            "unit": "x",
        },
        "pod_wire": {
            "metric": "pod wire overhead: median rpc_submit round "
                      "trip through an in-process localhost-TCP "
                      "HostAgent minus the loopback lane's, same "
                      "executor + tiny C2C workload "
                      f"(loopback {wire['loopback_us']:.0f} us vs "
                      f"TCP {wire['tcp_us']:.0f} us, "
                      f"{wire['repeats']} warmed repeats; "
                      "net.transport.wire_overhead_probe)",
            "value": round(wire["overhead_us"], 1),
            "unit": "us",
        },
        "pod_wire_pooled": {
            "metric": "pod wire overhead with connection pooling: "
                      "median rpc_submit round trip over a KEPT-ALIVE "
                      "pooled TCP lane minus the loopback lane's, "
                      "same agent + workload as pod_wire "
                      f"(TCP pooled {wire['tcp_pooled_us']:.0f} us vs "
                      f"fresh-connect {wire['tcp_us']:.0f} us, pool "
                      f"hits {wire['pool_hits']}/"
                      f"{wire['pool_hits'] + wire['pool_misses']}; "
                      "net.transport.wire_overhead_probe)",
            "value": round(wire["overhead_pooled_us"], 1),
            "unit": "us",
        },
        "wire_bytes_int8": {
            "metric": f"{n}^3 spherical-cutoff C2C distributed exchange "
                      f"aggregate wire bytes on the int8 rung (padded "
                      f"block layout, {shards} shards, per-stick f32 "
                      f"scales INCLUDED: {links * ms * 4} B of scales "
                      f"on {links * ms * mp * 2} B payload; f32 wire "
                      f"on the same layout {f32_wire} B, ratio "
                      f"{int8_wire / f32_wire:.4f})",
            "value": int(int8_wire),
            "unit": "bytes",
        },
        "wire_error_int8": {
            "metric": "measured end-to-end rel-l2 of the int8 wire "
                      f"rung: {wn}^3 spherical C2C backward on 2 "
                      "virtual shards vs the rung-0 twin, seeded "
                      "spectrum with 10^+-4 per-value dynamic range "
                      f"(plan probe err {w_int8.wire_probe_error:.2e}, "
                      f"declared budget {w_int8.wire_error_budget:g}, "
                      f"resolved rung {w_int8.wire_rung_name})",
            "value": round(wire_err, 6),
            "unit": "rel-l2",
        },
        "spmd_coalesce": {
            "metric": "cross-request SPMD coalescing: distributed "
                      "requests per collective round for a 12-request "
                      "same-signature burst through the pod coalescer "
                      f"(spmd_max_batch 8 -> {spmd_sig['spmd_launches']}"
                      f" launches, batch hist "
                      f"{spmd_sig['spmd_batch_hist']}; a drop means "
                      "the window splinters rounds)",
            "value": round(per_round, 2),
            "unit": "req/round",
        },
        "recorder_overhead": {
            "metric": "flight-recorder armed hot-path cost per "
                      "request: journal events + trace tail retention "
                      "with the recorder ON minus the same path "
                      "disarmed, deterministic synthetic-request "
                      "micro A/B (obs.recorder.overhead_probe, "
                      f"{rec['requests']} requests x {rec['repeats']} "
                      f"repeats, best-of; disarmed path "
                      f"{rec['off_us']:.2f} us/req, armed "
                      f"{rec['on_us']:.2f} us/req)",
            "value": round(rec["delta_us"], 2),
            "unit": "us",
        },
    }))


def run_sessions(k: int) -> None:
    """Run the measurement in k fresh subprocesses (each gets its own
    backend session) and emit the best session's JSON with the per-session
    values disclosed (outlier sessions flagged separately)."""
    results = []
    for i in range(k):
        env = dict(os.environ, SPFFT_BENCH_INNER="1",
                   SPFFT_BENCH_SKIP_BASELINE="1")
        proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                              capture_output=True, text=True, env=env)
        line = next((ln for ln in reversed(proc.stdout.splitlines())
                     if ln.startswith("{")), None)
        if line is None:
            sys.stderr.write(proc.stdout[-2000:] + proc.stderr[-2000:])
            raise SystemExit(f"bench session {i} produced no JSON")
        results.append(json.loads(line))
    best = min(results, key=lambda r: r["value"])
    kept, outliers = split_outlier_sessions([r["value"] for r in results])
    sessions_ms = ", ".join(f"{v * 1e3:.2f}" for v in kept)
    outlier_note = ("" if not outliers else
                    f"; {len(outliers)} outlier session(s) dropped: "
                    + ", ".join(f"{v * 1e3:.2f}" for v in outliers)
                    + " ms")
    if os.environ.get("SPFFT_BENCH_SKIP_BASELINE") == "1":
        baseline_s = 0.0
    else:
        baseline_s = baseline_only()
    best["metric"] += (f" [best of {k} backend sessions: {sessions_ms} ms"
                       f"{outlier_note}]"
                       f" (baseline=pocketfft[{os.cpu_count()}cpu] "
                       f"{baseline_s:.3f}s)")
    best["vs_baseline"] = (round(baseline_s / best["value"], 3)
                           if baseline_s else 0.0)
    best.update(symmetry_rows())
    print(json.dumps(best))


def baseline_only() -> float:
    """The CPU pocketfft baseline without touching the TPU backend."""
    from spfft_tpu.indexing import build_index_plan
    from spfft_tpu.types import TransformType
    from spfft_tpu.utils.workloads import spherical_cutoff_triplets
    n = int(os.environ.get("SPFFT_BENCH_DIM", "256"))
    triplets = spherical_cutoff_triplets(n)
    rng = np.random.default_rng(42)
    values = (rng.uniform(-1, 1, len(triplets))
              + 1j * rng.uniform(-1, 1, len(triplets))).astype(np.complex64)

    class _P:  # minimal plan view for cpu_baseline_pair_seconds
        index_plan = build_index_plan(TransformType.C2C, n, n, n,
                                      np.asarray(triplets))
    return cpu_baseline_pair_seconds(_P, values)


def cpu_baseline_pair_seconds(plan, values: np.ndarray, reps: int = 2) -> float:
    """The same sparse pipeline on CPU (pocketfft, workers=-1 i.e. all
    available cores), timed after one warm-up rep (first-touch allocation and
    pocketfft plan setup excluded, matching the warmed TPU measurement)."""
    from scipy import fft as sfft
    ip = plan.index_plan
    nz, ny, nxf = ip.dim_z, ip.dim_y, ip.dim_x_freq
    cols = ip.scatter_cols
    vi = ip.value_indices
    t0 = time.perf_counter()
    for rep in range(reps + 1):
        if rep == 1:
            t0 = time.perf_counter()  # discard the warm-up rep
        # backward: decompress -> z-IFFT -> scatter -> xy-IFFT
        sticks = np.zeros((ip.num_sticks * nz,), np.complex64)
        sticks[vi] = values
        sticks = sticks.reshape(ip.num_sticks, nz)
        sticks = sfft.ifft(sticks, axis=1, workers=-1) * nz
        grid = np.zeros((nz, ny * nxf), np.complex64)
        grid[:, cols] = sticks.T
        grid = grid.reshape(nz, ny, nxf)
        space = sfft.ifft2(grid, axes=(1, 2), workers=-1) * (ny * nxf)
        # forward: xy-FFT -> gather -> z-FFT -> compress
        grid = sfft.fft2(space, axes=(1, 2), workers=-1)
        sticks = grid.reshape(nz, ny * nxf)[:, cols].T
        sticks = np.ascontiguousarray(sticks)
        sticks = sfft.fft(sticks, axis=1, workers=-1)
        _ = sticks.reshape(-1)[vi]
    return (time.perf_counter() - t0) / reps


def main() -> None:
    if os.environ.get("SPFFT_BENCH_SYMMETRY_INNER") == "1":
        return symmetry_inner()
    k = int(os.environ.get("SPFFT_BENCH_SESSIONS", "4"))
    if "SPFFT_BENCH_INNER" not in os.environ and k > 1:
        return run_sessions(k)
    import jax
    from spfft_tpu import TransformType, make_local_plan
    from spfft_tpu.utils import as_interleaved
    from spfft_tpu.utils.workloads import spherical_cutoff_triplets

    n = int(os.environ.get("SPFFT_BENCH_DIM", "256"))
    reps = int(os.environ.get("SPFFT_BENCH_REPS", "30"))

    triplets = spherical_cutoff_triplets(n)
    rng = np.random.default_rng(42)
    values = (rng.uniform(-1, 1, len(triplets))
              + 1j * rng.uniform(-1, 1, len(triplets))).astype(np.complex64)

    jax.devices()  # backend bring-up (~7 s through the tunnel) is session
    # cost, not plan cost — keep it out of plan_s
    t_plan = time.perf_counter()
    plan = make_local_plan(TransformType.C2C, n, n, n, triplets,
                           precision="single")
    t_plan = time.perf_counter() - t_plan

    values_il = jax.device_put(
        np.asarray(as_interleaved(values, "single")))

    def sync(arr):
        # On remote-attached TPUs block_until_ready can return before the
        # device work completes; a host readback of one element is a hard
        # sync. Device programs execute FIFO per core, so syncing the last
        # enqueued output syncs the whole queue.
        return float(np.asarray(arr.ravel()[0]))

    # The benchmark pair through the public fused round-trip API
    # (plan.apply_pointwise with identity fn): one executable for
    # backward+forward — saves a dispatch round trip and lets XLA schedule
    # across the boundary (18.6 vs 25.6 ms at 256^3 on TPU v5e). The
    # separate backward call still produces the space field used for the
    # accuracy check.
    space = plan.backward(values_il)
    out = plan.apply_pointwise(values_il)  # warm-up / compile
    sync(out)

    # Variance-robust statistic: the hard-sync readback through the axon
    # tunnel costs ~85-130 ms regardless of queue depth (measured on a
    # ready array), so any "time N reps then sync" number includes
    # sync_cost/N of pure tunnel latency. The difference-of-group-sizes
    # estimator cancels the constant: pair = (medT(g2) - medT(g1)) /
    # (g2 - g1), medians over several samples per size so the bimodal
    # sync cost (see module docstring) cancels at the majority mode
    # instead of fabricating fast readings at mismatched pairings.
    from spfft_tpu.utils.benchtime import diff_estimate_seconds

    def timed(g):
        t0 = time.perf_counter()
        o = None
        for _ in range(g):
            o = plan.apply_pointwise(values_il)
        sync(o)
        return time.perf_counter() - t0

    est = diff_estimate_seconds(timed, reps=reps)
    pair_s, stat = est.seconds, est.label

    # accuracy: L2 error of the backward result vs a dense oracle
    st = triplets.copy()
    st = np.where(st < 0, st + n, st)
    cube = np.zeros((n, n, n), np.complex64)
    cube[st[:, 2], st[:, 1], st[:, 0]] = values
    from scipy import fft as sfft
    oracle = sfft.ifftn(cube, workers=-1) * cube.size
    got = np.asarray(space)
    got = got[..., 0] + 1j * got[..., 1]
    l2 = float(np.linalg.norm(got - oracle) / np.linalg.norm(oracle))

    if os.environ.get("SPFFT_BENCH_SKIP_BASELINE") == "1":
        baseline_s = 0.0
    else:
        baseline_s = cpu_baseline_pair_seconds(plan, values)

    # Effective bandwidth: logical bytes of the pair (each stage's elements
    # read + written once, c64 = 8 B; see scripts/profile_stages.py for the
    # per-stage model and the measured copy floor it compares against).
    ip = plan.index_plan
    sz = ip.num_sticks * ip.dim_z
    pair_bytes = (2 * ip.num_values + 8 * sz + 6 * n ** 3) * 8
    gbs = pair_bytes / pair_s / 1e9

    base_note = (f", baseline=pocketfft[{os.cpu_count()}cpu] "
                 f"{baseline_s:.3f}s" if baseline_s else "")
    result = {
        "metric": f"{n}^3 spherical-cutoff C2C fwd+bwd pair wall-clock, "
                  f"{stat} ("
                  f"l2_err_vs_dense={l2:.2e}, plan_s={t_plan:.2f}, "
                  f"n_values={len(triplets)}, "
                  f"effective_GBps={gbs:.0f}{base_note})",
        "value": round(pair_s, 6),
        "unit": "s",
        "vs_baseline": round(baseline_s / pair_s, 3) if baseline_s else 0.0,
    }
    if "SPFFT_BENCH_INNER" not in os.environ:
        result.update(symmetry_rows())  # single-session direct run
    print(json.dumps(result))


if __name__ == "__main__":
    main()

# Native components of spfft_tpu.
#
# `make native` builds the plan-time planner kernels (also auto-built on
# first import, see spfft_tpu/native/__init__.py); `make capi` builds the
# embeddable C API library libspfft_tpu.so (include/spfft_tpu.h);
# `make example-c` builds and runs the C example against it.

PY_INCLUDES := $(shell python3-config --includes)
PY_LDFLAGS  := $(shell python3-config --ldflags --embed)
CXX         ?= g++
CXXFLAGS    ?= -O3 -std=c++17 -Wall -fPIC

NATIVE_DIR  := spfft_tpu/native
CACHE_TAG   := $(shell python3 -c "import sys; print(sys.implementation.cache_tag)")
PLANNER_SO  := $(NATIVE_DIR)/_planner_$(CACHE_TAG).so
CAPI_SO     := lib/libspfft_tpu.so

.PHONY: all native capi example-c test ci ci-tpu trace-smoke \
        control-smoke fused-smoke store-smoke chaos-smoke \
        cluster-smoke pod-smoke bench-check lint analyze clean

# One-command CI (reference: .github/workflows/ci.yml builds + runs the
# local test matrix): full CPU suite (8-device virtual mesh; includes the
# capi build, C feature drive, Fortran-width execution and the in-suite
# multihost smoke), the compiled C example, the standalone 2-process
# multihost smoke, and the precision matrix in CPU mode. Record with
#   make ci 2>&1 | tee docs/ci_r05.log
ci: native capi
	@echo "== CI 1/4: test suite (CPU, virtual 8-device mesh) =="
	python -m pytest tests/ -q
	@echo "== CI 2/4: compiled C example =="
	$(MAKE) example-c
	@echo "== CI 3/4: 2-process multihost smoke =="
	python scripts/multihost_smoke.py
	@echo "== CI 4/4: precision matrix (CPU mode) =="
	JAX_PLATFORMS=cpu DIMS="32 64" python scripts/precision_matrix.py
	@echo "CI GREEN"

# Baseline lint (docs/static_analysis.md): pyflakes-family rules only
# (unused imports, undefined names; config under [tool.ruff] in
# pyproject.toml, scripts/probe_* excluded there). Uses a real ruff
# when the environment has one; otherwise the dependency-free built-in
# twin runs the same two rule families, so the gate never silently
# degrades to a no-op on a machine without ruff.
lint:
	@echo "== lint: baseline (ruff, or the built-in pyflakes-lite twin) =="
	@if command -v ruff >/dev/null 2>&1; then \
	  ruff check spfft_tpu/; \
	else \
	  echo "(ruff not installed; running python -m spfft_tpu.analysis --baseline-only)"; \
	  python -m spfft_tpu.analysis --baseline-only -q; \
	fi
	@echo "LINT GREEN"

# Project lint engine (docs/static_analysis.md): the AST-based checkers
# that enforce the contracts the code claims — lock-discipline over
# `#: guarded by _lock` fields + the lock-acquisition-order graph
# (deadlock-shape cycles fail), span-closure for every obs span open
# site, the spfft_* counter/series registry, the error taxonomy and the
# control-plane knob registry. Zero unwaived findings required; every
# waiver is listed in the report with its reason. The same checks run
# in tier-1 (tests/test_analysis.py::test_real_package_analysis_is_clean
# and the fixture suite around it).
analyze:
	@echo "== analyze: project static-analysis pass =="
	@mkdir -p build
	python -m spfft_tpu.analysis --json build/analysis_report.json
	@echo "ANALYZE GREEN"

# On-TPU regression lane (tests_tpu/): oracle matrix, forced Pallas,
# the segmented aliased-carry accumulate, split-x, pair-IO, two-stage
# axes and repeated-backward — the silent-corruption bug classes the
# CPU-pinned suite cannot see — plus the serving smokes (pinning +
# fault-injection: bucket isolation, device quarantine over the real
# chip pool, crash-proof dispatch). Needs the real chip; record with
#   make ci-tpu 2>&1 | tee docs/ci_tpu_r05.log
# lint + analyze + chaos-smoke + cluster-smoke + pod-smoke run first:
# the chip lane is expensive, so it never starts on a tree the static
# passes already know is dirty or whose failure semantics the CPU
# chaos harness / emulated pod / real-TCP pod can already break.
ci-tpu: lint analyze chaos-smoke cluster-smoke pod-smoke
	@echo "== CI-TPU: on-device regression lane =="
	python -m pytest tests_tpu/ -q -rA
	@echo "CI-TPU GREEN"

# Observability smoke (docs/observability.md): the deterministic serving
# smoke with request tracing on, exporting + validating both artifact
# formats — the Chrome trace JSON (all eight request stages + compile +
# exchange events, zero unclosed spans; open build/trace_smoke.json in
# https://ui.perfetto.dev) and the Prometheus text exposition
# (round-tripped through the validating parser). The same checks run in
# tier-1 (tests/test_serve_bench_cli.py::test_serve_bench_smoke_trace_artifacts).
trace-smoke:
	@echo "== trace-smoke: traced serve.bench --smoke + artifact validation =="
	@mkdir -p build
	python -m spfft_tpu.serve.bench --smoke --cpu --devices 2 \
	  --trace-out build/trace_smoke.json --prom-out build/trace_smoke.prom
	python -m spfft_tpu.obs validate build/trace_smoke.json --require-request-stages
	python -m spfft_tpu.obs prom build/trace_smoke.prom
	@echo "TRACE-SMOKE GREEN"

# Control-plane smoke (docs/control_plane.md): the traced deterministic
# serving smoke WITH the feedback controller on — the scripted
# queue-buildup trace must produce >= 1 recorded, bounds-clamped knob
# decision (the CLI exits 1 otherwise), zero unclosed spans, bit-exact
# results through a mid-stream retune, no SLO false positives, and the
# Prometheus text must expose the spfft_control_* / spfft_slo_* series.
# The same checks run in tier-1
# (tests/test_serve_bench_cli.py::test_serve_bench_smoke_control_closes_the_loop).
control-smoke:
	@echo "== control-smoke: traced serve.bench --smoke --control + assertions =="
	@mkdir -p build
	python -m spfft_tpu.serve.bench --smoke --control --cpu --devices 2 \
	  --trace-out build/control_smoke.json --prom-out build/control_smoke.prom
	grep -q "spfft_control_decisions_total" build/control_smoke.prom
	grep -q "spfft_slo_burn_rate" build/control_smoke.prom
	grep -q "spfft_control_knob" build/control_smoke.prom
	python -m spfft_tpu.obs validate build/control_smoke.json --require-request-stages
	@echo "CONTROL-SMOKE GREEN"

# Fused compression+DFT smoke (docs/kernels.md): the interpret-mode
# bit-exactness + fallback-gate suite for ops/fused_kernel.py, then a
# benchmark.py --fused run whose JSON must report the fused path ACTIVE
# with no gate declines, then the distributed twin under the overlap
# pipeline (K=2 compact exchange, r2c-trimmed stick set) which must
# report BOTH fused directions active with no per-direction declines.
# The same coverage runs in tier-1 (tests/test_fused_kernel.py,
# tests/test_fused_dist.py, tests/test_benchmark_cli.py::
# test_cli_fused_ab); on-chip bit-exactness + the profile evidence that
# the dense stick intermediate is gone live in `make ci-tpu`
# (test_fused_compression_dft_on_tpu, test_fused_overlap_on_tpu).
fused-smoke:
	@echo "== fused-smoke: interpret-mode fused compression+DFT checks =="
	@mkdir -p build
	python -m pytest tests/test_fused_kernel.py -q
	python -m spfft_tpu.benchmark -d 8 6 128 -r 1 --fused \
	  -o build/fused_smoke.json
	python -c "import json; p = json.load(open('build/fused_smoke.json'))['parameters']; assert p['fused'] and not p['fused_fallback'], p"
	SPFFT_TPU_COMPACT_PPERMUTE=1 SPFFT_TPU_FUSED_RECOMPUTE_LIMIT=16 \
	  python -m spfft_tpu.benchmark -d 8 6 128 -r 1 --fused --cpu \
	  --shards 2 -e compact --overlap-chunks 2 --transform r2c \
	  -o build/fused_dist_smoke.json
	python -c "import json; p = json.load(open('build/fused_dist_smoke.json'))['parameters']; assert p['fused_dist'] and not p['fused_dist_fallback'] and p['overlap_chunks'] == 2, p"
	@echo "FUSED-SMOKE GREEN"

# Plan-artifact store smoke (docs/artifact_cache.md): the zero-cold-
# start contract across REAL process boundaries — process A builds one
# canonical workload into a store (index tables + kernel tables + AOT
# executables, async-spilled), records a manifest and a backward-
# execution reference; process B (a fresh interpreter) prewarms from
# the manifest and must resolve the same request with builds==0, no
# registry-build/table-build compile events, and a bit-exact backward
# vs process A's recorded output (--strict exits 1 on any of those
# failing). The same checks run in tier-1
# (tests/test_plan_store.py::test_store_smoke_cross_process); the
# on-chip AOT-beats-fresh-compile assertion is staged in `make ci-tpu`
# (test_plan_store_on_tpu).
store-smoke:
	@echo "== store-smoke: cross-process plan-artifact warm boot =="
	@mkdir -p build; rm -rf build/store_smoke
	env JAX_PLATFORMS=cpu python -m spfft_tpu.serve.store seed \
	  build/store_smoke --dim 24 --use-pallas --reference --json
	env JAX_PLATFORMS=cpu python -m spfft_tpu.serve.store manifest \
	  build/store_smoke
	env JAX_PLATFORMS=cpu python -m spfft_tpu.serve.store prewarm \
	  build/store_smoke --manifest build/store_smoke/manifest.json \
	  --compile --check-reference --strict --json
	env JAX_PLATFORMS=cpu python -m spfft_tpu.serve.store verify \
	  build/store_smoke --json > /dev/null
	@echo "STORE-SMOKE GREEN"

# Chaos smoke (docs/serving.md "Failure semantics"): the seeded chaos
# harness on two deterministic seeds — the four degradation-ladder
# acceptance phases (runtime fused demotion, ENOSPC -> memory-only
# store, execute-timeout watchdog, pod lane death mid-trace) plus 16
# seeded multi-seam fault
# storms per seed across executor/plan/registry/store, asserting zero
# hangs, typed failures only, bit-exact healthy requests, zero
# unclosed spans and no torn store artifacts. Exit 1 on any violation.
# The same harness runs in tier-1
# (tests/test_serve_bench_cli.py::test_serve_bench_chaos_harness);
# the on-chip twin is staged in tests_tpu/test_chaos_on_tpu.py.
chaos-smoke:
	@echo "== chaos-smoke: seeded multi-seam fault storms =="
	@mkdir -p build
	env JAX_PLATFORMS=cpu python -m spfft_tpu.serve.bench --chaos 7 \
	  -o build/chaos_smoke_s7.json > /dev/null
	env JAX_PLATFORMS=cpu python -m spfft_tpu.serve.bench --chaos 1234 \
	  -o build/chaos_smoke_s1234.json > /dev/null
	@echo "CHAOS-SMOKE GREEN"

# Pod smoke (docs/cluster.md): the in-process 2-host emulated pod —
# 25 requests (single-device routed power-of-two-choices + one
# DistributedTransformPlan through the pod-wide SPMD lane) bit-exact
# vs direct plan execution, both hosts exercised, one trace id
# end-to-end across the host boundary with zero unclosed spans, the
# federated /metrics exposition re-parsed by the validating parser,
# host-death failover, and the routing-policy simulation gates
# (round-robin skew >= 4x, p2c <= 2x). Exit 1 on any violation. The
# same checks run in tier-1 (tests/test_cluster.py); the on-chip twin
# is staged in tests_tpu/test_pod_serve_on_tpu.py.
cluster-smoke:
	@echo "== cluster-smoke: emulated 2-host pod + routing simulation =="
	env JAX_PLATFORMS=cpu \
	  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	  python -m spfft_tpu.serve.cluster --smoke
	@echo "CLUSTER-SMOKE GREEN"

# Real-wire pod smoke (docs/cluster.md "Deployment"): two AGENT
# PROCESSES over localhost TCP behind a PodFrontend of TcpHostLanes —
# a mixed single+distributed trace bit-exact vs a serial oracle built
# in the parent, one trace id across the process boundary (asserted
# via the agents' `spans` RPC), a mid-stream join that boots warm off
# the shared blob tier (joiner registry builds == 0), kill -9 failover
# with bit-exact survivors, and a drain-leave walking the membership
# ladder. Exit 1 on any violation.
pod-smoke:
	@echo "== pod-smoke: real two-process pod over localhost TCP =="
	env JAX_PLATFORMS=cpu \
	  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	  python -m spfft_tpu.net.smoke
	@echo "POD-SMOKE GREEN"

# Perf-trajectory guard (scripts/bench_regress.py): run the north-star
# benchmark fresh and compare against the latest recorded BENCH_r*.json
# with a noise threshold — nonzero exit on regression, so the perf
# trajectory is machine-checked instead of eyeballed. Record with
#   make bench-check 2>&1 | tee docs/bench_check_rNN.log
bench-check:
	@echo "== bench-check: fresh benchmark vs latest BENCH_r*.json =="
	@mkdir -p build
	python bench.py | tee build/bench_fresh.log
	grep '^{' build/bench_fresh.log | tail -1 > build/bench_fresh.json
	python scripts/bench_regress.py --fresh build/bench_fresh.json
	@echo "BENCH-CHECK GREEN"

all: native capi

native: $(PLANNER_SO)

$(PLANNER_SO): $(NATIVE_DIR)/planner.cpp
	$(CXX) $(CXXFLAGS) -fopenmp -shared $< -o $@

capi: $(CAPI_SO)

$(CAPI_SO): $(NATIVE_DIR)/capi.cpp include/spfft_tpu.h
	@mkdir -p lib
	$(CXX) $(CXXFLAGS) -shared -Iinclude $(PY_INCLUDES) $< -o $@ $(PY_LDFLAGS)

example-c: $(CAPI_SO)
	@mkdir -p build
	$(CXX) -O2 -Iinclude examples/example.c -o build/example_c -Llib \
	  -lspfft_tpu -Wl,-rpath,'$$ORIGIN/../lib'
	SPFFT_TPU_PACKAGE_PATH=$(CURDIR) ./build/example_c

test:
	python -m pytest tests/ -q

clean:
	rm -rf lib build $(NATIVE_DIR)/_planner_*.so

# Native components of spfft_tpu.
#
# `make native` builds the plan-time planner kernels (also auto-built on
# first import, see spfft_tpu/native/__init__.py); `make capi` builds the
# embeddable C API library libspfft_tpu.so (include/spfft_tpu.h);
# `make example-c` builds and runs the C example against it.

PY_INCLUDES := $(shell python3-config --includes)
PY_LDFLAGS  := $(shell python3-config --ldflags --embed)
CXX         ?= g++
CXXFLAGS    ?= -O3 -std=c++17 -Wall -fPIC

NATIVE_DIR  := spfft_tpu/native
CACHE_TAG   := $(shell python3 -c "import sys; print(sys.implementation.cache_tag)")
PLANNER_SO  := $(NATIVE_DIR)/_planner_$(CACHE_TAG).so
CAPI_SO     := lib/libspfft_tpu.so

.PHONY: all native capi example-c test clean

all: native capi

native: $(PLANNER_SO)

$(PLANNER_SO): $(NATIVE_DIR)/planner.cpp
	$(CXX) $(CXXFLAGS) -fopenmp -shared $< -o $@

capi: $(CAPI_SO)

$(CAPI_SO): $(NATIVE_DIR)/capi.cpp include/spfft_tpu.h
	@mkdir -p lib
	$(CXX) $(CXXFLAGS) -shared -Iinclude $(PY_INCLUDES) $< -o $@ $(PY_LDFLAGS)

example-c: $(CAPI_SO)
	@mkdir -p build
	$(CXX) -O2 -Iinclude examples/example.c -o build/example_c -Llib \
	  -lspfft_tpu -Wl,-rpath,'$$ORIGIN/../lib'
	SPFFT_TPU_PACKAGE_PATH=$(CURDIR) ./build/example_c

test:
	python -m pytest tests/ -q

clean:
	rm -rf lib build $(NATIVE_DIR)/_planner_*.so
